"""The compiled SPMD train step and its builders.

This single module replaces the reference's entire synchronization stack
(SURVEY.md §3.1–§3.2): per-variable ``ConditionalAccumulator``s on PS tasks,
the chief's ``take_grad(N)`` aggregation thread, the token ``FIFOQueue``
barrier, and ``MonitoredTrainingSession``'s chief/worker session dance
(TF sync_replicas_optimizer.py:215-338; monitored_session.py:428).

The TPU-native form: the batch is one global array sharded over the ``data``
mesh axis; parameters are replicated (or sharded over ``model`` for tensor
parallelism); the loss is a global mean.  ``jax.grad`` of that mean makes XLA
emit a partial gradient per chip plus an all-reduce over ICI — the whole
accumulator/token protocol becomes one fused collective inside one compiled
program, and the barrier is implicit in the collective's semantics.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Callable, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from distributed_tensorflow_models_tpu import telemetry
from distributed_tensorflow_models_tpu.core import sharding as shardlib
from distributed_tensorflow_models_tpu.core.train_state import TrainState
from distributed_tensorflow_models_tpu.ops import ema as emalib
from distributed_tensorflow_models_tpu.ops import losses as losslib
from distributed_tensorflow_models_tpu.ops import metrics as metriclib

log = logging.getLogger("dtm")

PyTree = Any
Batch = Mapping[str, jax.Array]
# loss_fn(params, state, batch, rngs) -> (loss, aux) where aux is a dict
# that may carry: 'metrics' (dict of scalars), 'batch_stats' (updated BN
# state), 'carry' (updated recurrent state).  Omitted keys mean "unchanged".
LossFn = Callable[
    [PyTree, TrainState, Batch, Mapping[str, jax.Array]],
    tuple[jax.Array, dict],
]


def classification_loss_fn(
    apply_fn: Callable,
    *,
    label_smoothing: float = 0.0,
    weight_decay: float = 0.0,
    aux_loss_weight: float = 0.0,
) -> LossFn:
    """Forward + loss for image-classification models.

    Covers every CNN config in the reference zoo (SURVEY.md §2.1 R3-R7):
    plain softmax cross entropy; slim-style L2 weight decay on kernels;
    label smoothing and the 0.4-weighted auxiliary-logits head for
    Inception-v3 (R5).  Models return either ``logits`` or
    ``(logits, aux_logits)``.
    """

    def loss_fn(params, state, batch, rngs):
        batch_stats = state.batch_stats
        variables = {"params": params}
        has_bn = bool(jax.tree_util.tree_leaves(batch_stats))
        if has_bn:
            variables["batch_stats"] = batch_stats
            outputs, updated = apply_fn(
                variables,
                batch["image"],
                train=True,
                rngs=dict(rngs),
                mutable=["batch_stats"],
            )
            new_batch_stats = updated["batch_stats"]
        else:
            outputs = apply_fn(
                variables, batch["image"], train=True, rngs=dict(rngs)
            )
            new_batch_stats = batch_stats
        if isinstance(outputs, (tuple, list)):
            logits, aux_logits = outputs[0], outputs[1]
        else:
            logits, aux_logits = outputs, None

        labels = batch["label"]
        xent = losslib.mean_softmax_cross_entropy(
            logits, labels, label_smoothing
        )
        loss = xent
        if aux_logits is not None and aux_loss_weight:
            loss = loss + aux_loss_weight * losslib.mean_softmax_cross_entropy(
                aux_logits, labels, label_smoothing
            )
        if weight_decay:
            loss = loss + losslib.l2_weight_decay(params, weight_decay)
        metrics = {
            "loss": loss,
            "xent": xent,
            "accuracy": metriclib.accuracy(logits, labels),
        }
        return loss, {"metrics": metrics, "batch_stats": new_batch_stats}

    return loss_fn


def lm_loss_fn(apply_fn: Callable, fused_unembed: bool = False) -> LossFn:
    """Forward + loss for the PTB LSTM (SURVEY.md §2.1 R8).

    ``fused_unembed=True`` routes the head projection + cross entropy
    through :func:`...ops.losses.chunked_unembed_xent` (the model must
    accept ``return_hidden=True`` — the transformer does); bfloat16 MXU
    matmul, f32 accumulation, O(chunk, V) peak memory instead of
    O(B·T·V).

    Batch keys: ``inputs`` and ``targets``, both ``[B, T]`` int32 (targets
    are inputs shifted by one token, the reference PTB reader convention).
    The model consumes and returns the recurrent carry; the carry is read
    from ``state.carry`` and the updated value is returned through aux, so
    truncated-BPTT state threads across segments exactly as the reference
    threads final LSTM state into the next ``session.run`` (SURVEY.md
    §7.4.5).  Gradients do not flow into previous segments — the carry
    enters as a leaf input, which *is* truncation.

    Metrics include ``nll`` (mean per-token negative log-likelihood);
    perplexity = ``exp(nll)`` as the reference reports it.

    Models may ``sow`` scalar regularizers into the ``losses`` collection
    (the transformer's Switch-MoE load-balancing loss does); every leaf is
    summed into the objective but kept out of ``nll`` so perplexity stays
    comparable across dense and MoE configs.
    """

    def loss_fn(params, state, batch, rngs):
        if fused_unembed:
            # Fused path: the model stops at the post-ln_f hidden states
            # and the head projection + xent run chunked in one op —
            # never materializing [B*T, V] f32 logits
            # (ops/losses.py::chunked_unembed_xent).
            (hidden, new_carry), updated = apply_fn(
                {"params": params},
                batch["inputs"],
                carry=state.carry,
                train=True,
                rngs=dict(rngs),
                mutable=["losses"],
                return_hidden=True,
            )
            head = params["head"]
            nll = jnp.mean(
                losslib.chunked_unembed_xent(
                    hidden,
                    head["kernel"],
                    head.get("bias"),
                    batch["targets"],
                )
            )
        else:
            (logits, new_carry), updated = apply_fn(
                {"params": params},
                batch["inputs"],
                carry=state.carry,
                train=True,
                rngs=dict(rngs),
                mutable=["losses"],
            )
            nll = jnp.mean(
                losslib.softmax_cross_entropy(logits, batch["targets"])
            )
        aux = sum(
            jnp.sum(leaf)
            for leaf in jax.tree_util.tree_leaves(updated.get("losses", {}))
        )
        loss = nll + aux
        metrics = {"loss": loss, "nll": nll}
        if updated.get("losses"):
            metrics["aux_loss"] = aux
        return loss, {"metrics": metrics, "carry": new_carry}

    return loss_fn


def make_train_step(
    loss_fn: LossFn,
    rng_names: Sequence[str] = ("dropout",),
    donate: bool | None = None,
) -> Callable[[TrainState, Batch, jax.Array], tuple[TrainState, dict]]:
    """Build the jitted ``(state, batch, rng) -> (state, metrics)`` step.

    Equivalent of the whole worker-side hot loop in SURVEY.md §3.1 plus the
    chief's §3.2 aggregation duties, compiled to one XLA program.  The step
    is deterministic given ``rng`` and ``state.step`` (per-step keys are
    derived by ``fold_in``), which is what makes the distributed run
    reproducible — no arrival-order races as in the reference's async mode
    (SURVEY.md §5.2).

    ``donate`` defaults to True on accelerators (in-place state update —
    halves HBM pressure for the params/opt_state pytrees) with two
    environment carve-outs where donation is broken, both observed on this
    machine:

    - CPU: the XLA CPU thunk runtime can wedge its in-process collective
      rendezvous when donated buffers and cross-partition all-reduces mix on
      a small host thread pool (one partition never reaches the rendezvous;
      the runtime aborts after 40 s).  CPU is only used for fake-mesh
      testing, where donation buys nothing anyway.
    - The axon TPU relay (``PALLAS_AXON_POOL_IPS`` set): executions with
      input-output buffer aliasing fail with ``INVALID_ARGUMENT``.
    """
    if donate is None:
        donate = _default_donate()
    step_fn = make_train_step_fn(loss_fn, rng_names)

    def one_step(state: TrainState, batch: Batch, rng: jax.Array):
        # Compiled as the K=1 instance of the fused multi-step program —
        # the exact lax.scan body :func:`make_multi_step` runs.  XLA
        # optimizes a while-loop body slightly differently from the same
        # math as straight-line code (measured ~1e-7 param drift per step
        # on the CPU fake mesh), so sharing the scan form is what makes
        # ``steps_per_loop ∈ {1, K}`` trajectories bit-identical rather
        # than merely close (tests/test_train_loop.py pins this; scan
        # programs of different lengths agree exactly).  The length-1
        # expand/squeeze is free: layout-only ops inside the jit.
        chunk = jax.tree.map(lambda x: x[None], batch)

        def body(s, b):
            return step_fn(s, b, rng)

        new_state, rows = jax.lax.scan(body, state, chunk)
        return new_state, jax.tree.map(lambda x: x[0], rows)

    return jax.jit(one_step, donate_argnums=(0,) if donate else ())


def _default_donate() -> bool:
    """Donation auto-detection shared by the single-step and fused
    multi-step builders (see :func:`make_train_step`'s docstring for the
    two environment carve-outs).  ``DTM_DONATE=1/0`` overrides — the
    relay's INVALID_ARGUMENT on aliasing may get fixed upstream, and a
    one-env retry is how we find out without a code change."""
    import os

    env = os.environ.get("DTM_DONATE")
    if env is not None:
        return env != "0"
    return jax.default_backend() != "cpu" and not os.environ.get(
        "PALLAS_AXON_POOL_IPS"
    )


def make_multi_step(
    loss_fn: LossFn,
    unroll: int = 1,
    rng_names: Sequence[str] = ("dropout",),
    donate: bool | None = None,
) -> Callable[[TrainState, Batch, jax.Array], tuple[TrainState, dict]]:
    """Fused K-step train program: one dispatch, one device→host metrics
    transfer per *chunk* of K steps instead of per step.

    ``lax.scan``s the raw step over batches stacked on a new leading axis
    (``data/pipeline.py::BatchStacker`` assembles them): the returned
    jitted callable maps ``(state, stacked_batches, rng) ->
    (state, stacked_metrics)`` where every metrics leaf gains a leading
    length-K axis — per-step rows, accumulated on device, fetched in one
    transfer (or lazily, row by row, by the hook layer).

    Trajectory equivalence with K dispatches of :func:`make_train_step` is
    exact, not approximate, because every per-step dependency threads
    through the scan carry exactly as it threads through the host loop:

    - **rng**: per-step keys derive from ``fold_in(rng, state.step)`` with
      the *in-carry* step, so step ``s`` draws identical randomness
      whichever loop ran it;
    - **BN/carry**: ``batch_stats`` and the recurrent ``carry`` ride the
      ``TrainState`` carry, so step ``s+1`` sees step ``s``'s statistics;
    - **donation**: the chunk program donates the input state into the
      scan carry (same carve-outs as the single step), so HBM pressure
      does not grow with K.

    K is a trace-time constant (the stacked leading dim): each distinct
    chunk length compiles its own program, so drivers should stick to one
    K plus the few shrunken boundary tails.  ``unroll`` is forwarded to
    ``lax.scan`` (bigger compiled program, more cross-step overlap for
    XLA to find; 1 — the default — compiles fastest).
    """
    if donate is None:
        donate = _default_donate()
    return _jit_multi_step(
        make_train_step_fn(loss_fn, rng_names), unroll=unroll, donate=donate
    )


def _jit_multi_step(
    step_fn: Callable,
    unroll: int = 1,
    donate: bool | None = None,
) -> Callable:
    """Jit ``lax.scan`` of an already-built raw step (the
    :func:`make_train_step_fn` contract) over stacked batches — the
    entry point for callers that hold a step fn rather than a loss fn
    (bench.py's steps_per_loop sweep)."""
    if donate is None:
        donate = _default_donate()

    def multi_step_fn(state: TrainState, batches: Batch, rng: jax.Array):
        def body(s, batch):
            s, metrics = step_fn(s, batch, rng)
            return s, metrics

        return jax.lax.scan(body, state, batches, unroll=unroll)

    return jax.jit(multi_step_fn, donate_argnums=(0,) if donate else ())


class InstrumentedStep:
    """Wrap a jitted train step with compile + dispatch telemetry.

    jit compiles silently inside the first call (and again on every new
    input signature), which makes two production failure classes
    invisible: a recompile storm (shape or sharding instability re-paying
    the compile cost every few steps) and compile time masquerading as
    slow steps.  This wrapper surfaces both without changing execution
    semantics — every call still goes through the wrapped jit, keeping
    its implicit-resharding tolerance (an AOT ``lower().compile()``
    executable is stricter: it *rejects* inputs whose sharding drifted,
    e.g. a checkpoint-restored TP state, where jit just recompiles).

    - **Compile events**: the jit's compilation-cache size is read before
      and after each call (~0.05 µs); a growth means that call compiled,
      and its wall time is recorded into the ``train/compile`` timer
      (count = compile events, total = seconds — compile-dominated, one
      dispatch's enqueue time included).  Works for *every* recompile
      trigger, including sharding changes a batch-shape key would miss.
    - **FLOPs**: per new batch signature (leaf shapes/dtypes), a
      trace-only ``lower()`` + XLA cost analysis feeds the
      ``train/flops_per_step`` gauge (the *current* program's cost) and,
      per executed step, the per-signature FLOPs accumulate into the
      ``train/flops_total`` counter — the MFU numerator.  The counter,
      not ``gauge × steps``, is what MFU readers use, so a ragged final
      batch (smaller program, new signature) scales the accounting for
      *its* steps only instead of silently re-pricing the whole run
      (bench.py's single-step convention; Pallas custom-calls count zero
      FLOPs, so MFU is conservative, never inflated).  Tracing happens
      *before* the call, while input buffers are still valid under
      donation.
    - **Dispatch**: non-compiling calls are timed into ``train/dispatch``
      (host-side enqueue under async dispatch — the data-wait vs
      dispatch split is the diagnostic, not a device profile).
    """

    def __init__(
        self,
        step_fn: Callable,
        registry: Optional[telemetry.MetricsRegistry] = None,
        aot: Optional[object] = None,
    ):
        self._fn = step_fn
        self._registry = (
            registry if registry is not None else telemetry.get_registry()
        )
        # Optional ahead-of-time handle (harness/startup.py::AotTrainStep):
        # when its batch signature matches a call's, the pre-compiled
        # executable runs instead of the jit dispatch.  The FIRST AOT use
        # is accounted as the run's compile event (one train/compile
        # record covering the join-on-in-flight-compile remainder plus
        # that dispatch) so compile/dispatch counts stay exactly what the
        # jit path produces — per-signature: one compile, then dispatches.
        self._aot = aot
        self._flops_by_sig: dict = {}
        self.flops_per_step: Optional[float] = None

    @staticmethod
    def _signature(batch) -> tuple:
        return tuple(
            (tuple(leaf.shape), str(leaf.dtype))
            for leaf in jax.tree_util.tree_leaves(batch)
        )

    def _cache_size(self) -> Optional[int]:
        try:
            return self._fn._cache_size()
        except Exception:  # noqa: BLE001 — non-jitted callable
            return None

    def _record_flops(self, state, batch, rng) -> float:
        """Trace-only lowering -> unoptimized-HLO FLOPs (no backend
        compile; matches compiled FLOPs for matmul/conv-dominated graphs
        — see bench.py's verification).  Best-effort: telemetry must
        never be the thing that fails training."""
        flops = 0.0
        try:
            cost = self._fn.lower(state, batch, rng).cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            flops = max(float(cost["flops"]), 0.0)
        except Exception as e:  # noqa: BLE001 — per-platform availability
            log.debug("step FLOPs unavailable: %s", e)
        if flops > 0:
            self.flops_per_step = flops
            self._registry.gauge(telemetry.FLOPS_PER_STEP).set(flops)
        return flops

    def _call_timed(self, sig, state, batch, rng):
        """Run the step via the AOT executable (signature match) or the
        jit fn, timed into exactly one compile-or-dispatch record.  The
        compile classification covers both triggers: a jit cache growth,
        or the first use of the AOT program (whose record includes any
        blocking on the still-in-flight background compile)."""
        before = self._cache_size()
        t0 = time.perf_counter()
        fn, used_aot, aot_first = self._fn, False, False
        if self._aot is not None:
            exe, aot_first = self._aot.acquire(sig)
            if exe is not None:
                fn, used_aot = exe, True
        try:
            out = fn(state, batch, rng)
        except TypeError:
            if not used_aot:
                raise
            # An AOT executable is stricter than jit: it REJECTS inputs
            # whose avals/shardings drifted with a TypeError raised
            # BEFORE executing, so no buffers were consumed and the jit
            # retry is safe even under donation.  Deliberately narrow —
            # a mid-execution runtime failure may already have
            # invalidated donated inputs, and retrying would mask the
            # real error with "Array has been deleted"; those propagate.
            log.warning(
                "AOT train-step executable rejected the call; falling "
                "back to the jit path", exc_info=True,
            )
            self._aot.disable()
            out = self._fn(state, batch, rng)
        dt = time.perf_counter() - t0
        compiled = aot_first or (
            before is not None and self._cache_size() != before
        )
        name = telemetry.COMPILE if compiled else telemetry.DISPATCH
        self._registry.timer(name).record(dt)
        tr = self._registry.trace
        if tr.enabled:
            # The dispatch/compile split on the flight-recorder timeline:
            # compile events are rare and load-bearing (a recompile storm
            # is visible as a train of them); dispatches bound the ring's
            # reach, which is the ring's job.
            tr.complete(
                name, dt, ts_mono=t0,
                args={"aot": True} if used_aot else None,
            )
        return out

    def __call__(self, state, batch, rng):
        reg = self._registry
        sig = self._signature(batch)
        flops = self._flops_by_sig.get(sig)
        if flops is None:
            if self._flops_by_sig:
                log.warning(
                    "train step saw a new batch signature %s (%d prior) "
                    "— recompile storms show up as a growing compile "
                    "count in telemetry",
                    sig,
                    len(self._flops_by_sig),
                )
            flops = self._flops_by_sig[sig] = self._record_flops(
                state, batch, rng
            )
        out = self._call_timed(sig, state, batch, rng)
        if flops:
            reg.counter(telemetry.FLOPS_TOTAL).inc(flops)
        return out


class InstrumentedMultiStep(InstrumentedStep):
    """Chunk-aware :class:`InstrumentedStep` for the fused multi-step
    program: ``__call__(state, stacked_batches, rng)`` where the stacked
    leading axis is the chunk length K.

    Telemetry stays comparable across ``steps_per_loop`` values:

    - **FLOPs per chunk = K × the per-step signature cost.**  XLA cost
      analysis visits a scan/while body ONCE, ignoring the trip count
      (bench.py's empirically verified trap), so analysing the chunk
      program would under-count by exactly K.  Instead the per-step cost
      comes from a trace-only lowering of the raw single step
      (``flops_step_fn``) on one unstacked batch row, and the
      ``train/flops_total`` counter advances by K× that per executed
      chunk — so MFU readers see the same numerator either loop produces.
    - **Dispatch/compile**: one ``train/dispatch`` (or ``train/compile``)
      record per chunk — the per-chunk host cost IS the quantity the
      fused loop exists to amortise, so it is recorded raw; per-step
      comparisons divide by K (TelemetryHook's ``dispatch_s`` reads
      per-chunk under K>1, documented in README "Performance").

    ``train/step_time`` (chunk wall ÷ K) is recorded by the driver, which
    owns the full-iteration clock.
    """

    def __init__(
        self,
        multi_fn: Callable,
        flops_step_fn: Optional[Callable] = None,
        registry: Optional[telemetry.MetricsRegistry] = None,
        aot: Optional[object] = None,
    ):
        super().__init__(multi_fn, registry, aot=aot)
        self._flops_fn = (
            jax.jit(flops_step_fn) if flops_step_fn is not None else None
        )

    def _record_flops(self, state, batches, rng) -> float:
        """Per-STEP FLOPs from the raw single step on batch row 0 (one
        device gather per new signature; trace-only lowering after that).
        Best-effort, like the parent."""
        if self._flops_fn is None:
            return 0.0
        flops = 0.0
        try:
            row = jax.tree.map(lambda x: x[0], batches)
            cost = self._flops_fn.lower(state, row, rng).cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            flops = max(float(cost["flops"]), 0.0)
        except Exception as e:  # noqa: BLE001 — per-platform availability
            log.debug("multi-step FLOPs unavailable: %s", e)
        if flops > 0:
            self.flops_per_step = flops
            self._registry.gauge(telemetry.FLOPS_PER_STEP).set(flops)
        return flops

    def __call__(self, state, batches, rng):
        reg = self._registry
        k = jax.tree_util.tree_leaves(batches)[0].shape[0]
        sig = self._signature(batches)
        flops = self._flops_by_sig.get(sig)
        if flops is None:
            # New signature == new chunk length or batch shape; each
            # compiles its own scan program.  The driver keeps the set
            # small (one main K plus boundary tails), so tolerate a few
            # before raising the parent's recompile-storm diagnostic —
            # a shape-unstable dataset must still be surfaced.
            if len(self._flops_by_sig) >= 3:
                log.warning(
                    "fused train step saw a new chunk signature %s "
                    "(%d prior — expected one main K plus a few "
                    "boundary tails); recompile storms show up as a "
                    "growing compile count in telemetry",
                    sig,
                    len(self._flops_by_sig),
                )
            flops = self._flops_by_sig[sig] = self._record_flops(
                state, batches, rng
            )
        out = self._call_timed(sig, state, batches, rng)
        if flops:
            reg.counter(telemetry.FLOPS_TOTAL).inc(flops * k)
        return out


def per_step_rngs(
    rng: jax.Array, salt: jax.Array | int, rng_names: Sequence[str]
) -> dict[str, jax.Array]:
    """Derive the per-step named rng dict: ``fold_in`` the step (or event)
    counter, then one fold per rng name.  Shared by the sync train step and
    the async-PS emulator so their trajectories agree by construction."""
    step_rng = jax.random.fold_in(rng, salt)
    return {
        name: jax.random.fold_in(step_rng, i)
        for i, name in enumerate(rng_names)
    }


def apply_gradients(state: TrainState, grads: PyTree, aux: dict) -> TrainState:
    """Optimizer update + state advance from one grad computation's output.

    Consumes the full ``aux`` contract of :data:`LossFn` (``batch_stats``,
    ``carry``) and maintains the EMA shadows — the single place where a
    gradient becomes a new :class:`TrainState`, used by both the sync SPMD
    step and the async-PS emulation (TF optimizer.py:656's
    ``apply_gradients`` role)."""
    updates, new_opt_state = state.tx.update(
        grads, state.opt_state, state.params
    )
    new_params = optax.apply_updates(state.params, updates)
    new_ema = state.ema_params
    if state.ema_params is not None:
        new_ema = emalib.update_ema(
            state.ema_params,
            new_params,
            state.ema_decay,
            num_updates=state.step,
        )
    return state.replace(
        step=state.step + 1,
        params=new_params,
        batch_stats=aux.get("batch_stats", state.batch_stats),
        opt_state=new_opt_state,
        ema_params=new_ema,
        carry=aux.get("carry", state.carry),
    )


def make_train_step_fn(
    loss_fn: LossFn,
    rng_names: Sequence[str] = ("dropout",),
) -> Callable[[TrainState, Batch, jax.Array], tuple[TrainState, dict]]:
    """The raw (unjitted) step — compose into larger compiled programs,
    e.g. ``lax.scan`` over many steps for single-dispatch epochs/benchmarks
    (amortises host round-trips, lets XLA overlap across step boundaries)."""

    def step_fn(state: TrainState, batch: Batch, rng: jax.Array):
        rngs = per_step_rngs(rng, state.step, rng_names)
        grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
        (_, aux), grads = grad_fn(state.params, state, batch, rngs)
        metrics = dict(aux.get("metrics", {}))
        metrics["grad_norm"] = optax.global_norm(grads)
        return apply_gradients(state, grads, aux), metrics

    return step_fn


def state_is_finite(state: TrainState) -> bool:
    """True when every float leaf of the *trajectory-carrying* state —
    params, batch_stats, carry, opt_state, EMA shadows — is finite: the
    rollback path's checkpoint-candidate gate (``nan_policy="rollback"``).
    A checkpoint saved after divergence began must not be restored as a
    rollback target, or the retry replays the poison
    ``rollback_budget`` times; opt_state matters as much as params (an
    inf Adam second moment zeroes its update, leaving params finite
    while the optimizer is already poisoned).  One reduction per leaf,
    one scalar sync total — cheap enough for the (rare) rollback path,
    never on the hot path."""
    leaves = [
        leaf
        for tree in (
            state.params,
            state.batch_stats,
            state.carry,
            state.opt_state,
            state.ema_params,
        )
        for leaf in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating)
    ]
    if not leaves:
        return True
    return bool(
        jnp.all(jnp.stack([jnp.all(jnp.isfinite(leaf)) for leaf in leaves]))
    )


def make_eval_step(
    apply_fn: Callable, use_ema: bool = True
) -> Callable[[TrainState, Batch], dict]:
    """Jitted eval step returning top-1/top-5 *counts* (summed over the
    global batch, so the host just accumulates integers across batches —
    the reference eval loop's counting scheme, SURVEY.md §3.5)."""

    def eval_fn(state: TrainState, batch: Batch):
        params = state.eval_params if use_ema else state.params
        variables = {"params": params}
        if jax.tree_util.tree_leaves(state.batch_stats):
            variables["batch_stats"] = state.batch_stats
        outputs = apply_fn(variables, batch["image"], train=False)
        logits = (
            outputs[0] if isinstance(outputs, (tuple, list)) else outputs
        )
        labels = batch["label"]
        # Rows with label < 0 are padding (partial final eval batches padded
        # up to the mesh size) and are excluded from every count.
        valid = (labels >= 0).astype(jnp.float32)
        return {
            "top1_count": jnp.sum(
                metriclib.top_k_correct(logits, labels, 1) * valid
            ),
            "top5_count": jnp.sum(
                metriclib.top_k_correct(logits, labels, 5) * valid
            ),
            "count": jnp.sum(valid),
            "xent_sum": jnp.sum(
                losslib.softmax_cross_entropy(
                    logits, jnp.maximum(labels, 0)
                )
                * valid
            ),
        }

    return jax.jit(eval_fn)


def _collective_free_put(x, s):
    """``device_put`` onto ``s`` without cross-process collectives.

    ``jax.device_put`` onto a sharding that spans processes runs a
    value-equality broadcast of the *whole tensor* per leaf
    (``multihost_utils.assert_equal``), so laying out a model issues one
    cross-host collective per parameter before training starts.  Besides
    the startup cost, those broadcasts overlap in flight with the
    placement transfers and can interleave on the wire.  Every caller
    here holds the full global value on every process (same seed, same
    init), so each process can contribute its local shards directly and
    skip the wire entirely.
    """
    if s.is_fully_addressable:
        return jax.device_put(x, s)
    x = np.asarray(x)
    arrs = [
        jax.device_put(x[idx], d)
        for d, idx in s.addressable_devices_indices_map(x.shape).items()
    ]
    return jax.make_array_from_single_device_arrays(x.shape, s, arrs)


def place_state(
    state: TrainState,
    mesh: Mesh,
    param_rules: Sequence[shardlib.ShardingRule] = (),
) -> TrainState:
    """Lay the train state out on the mesh.

    With no rules everything is replicated — classic data parallelism, the
    reference's sync mode minus the parameter servers.  ``param_rules``
    shard selected weight dimensions over the ``model`` axis (tensor
    parallelism); optimizer slots and EMA shadows follow their parameters'
    sharding automatically, the analogue of TF slot variables inheriting
    their primary's PS placement (TF optimizer.py:463,
    device_setter.py:92-125).  Placement is collective-free: every
    process holds the full initial state, so global arrays are assembled
    from local shards (``_collective_free_put``) rather than broadcast.
    """
    param_sh = shardlib.tree_param_shardings(mesh, state.params, param_rules)

    def follow(template_sh, tree):
        """Shard `tree` leaves like the params leaf they parallel, replicating
        anything that has no parameter analogue (counts, scalars)."""
        flat_params = {
            shardlib._path_str(p): s
            for p, s in jax.tree_util.tree_leaves_with_path(template_sh)
        }

        def one(path, leaf):
            name = shardlib._path_str(path)
            for pname, s in flat_params.items():
                if name.endswith(pname) and leaf.ndim == len(s.spec):
                    return _collective_free_put(leaf, s)
            return _collective_free_put(leaf, shardlib.replicated(mesh))

        return jax.tree_util.tree_map_with_path(one, tree)

    return state.replace(
        step=_collective_free_put(state.step, shardlib.replicated(mesh)),
        params=jax.tree.map(_collective_free_put, state.params, param_sh),
        batch_stats=jax.tree.map(
            lambda x: _collective_free_put(x, shardlib.replicated(mesh)),
            state.batch_stats,
        ),
        opt_state=follow(param_sh, state.opt_state),
        ema_params=(
            None
            if state.ema_params is None
            else jax.tree.map(
                _collective_free_put, state.ema_params, param_sh
            )
        ),
        # Recurrent carry is batch-major activation state: shard over data.
        carry=(
            None
            if state.carry is None
            else jax.tree.map(
                lambda x: _collective_free_put(
                    x, shardlib.batch_sharding(mesh, x.ndim)
                ),
                state.carry,
            )
        ),
    )
