"""Asynchronous parameter-server emulation (SURVEY.md §7.6).

The one reference behavior with no natural SPMD analogue: in async-PS mode
each worker computes gradients against a *stale* parameter snapshot and
applies them straight into PS variable memory with no coordination
(SURVEY.md §3.3; TF optimizer.py:656 unlocked applies).  Convergence
degrades with staleness; the reference's headline experiment is the
async-vs-sync A/B on ResNet-50 (SURVEY.md §2.1 R6, BASELINE [B:10]).

This module reproduces those *semantics* deterministically, above the
compiled layer:

- ``num_workers`` virtual workers each hold a parameter snapshot tagged
  with the canonical step at fetch time.
- A schedule (round-robin, or seeded-random for arrival-order jitter)
  picks which worker acts at each event — the deterministic-replay knob.
- The picked worker computes gradients at its snapshot (compiled step),
  the coordinator applies them to the canonical state (compiled apply),
  and the worker refetches.  ``staleness = canonical_step - snapshot_step``
  is logged per event.
- ``staleness_limit`` reproduces the ConditionalAccumulator's
  stale-gradient *drop* (TF sync_replicas_optimizer.py:275-293 — grads
  stamped with an old ``local_step`` are discarded); the reference's
  accumulators drop, so dropped events still cost a fetch but no apply.

With ``num_workers=1`` the trajectory is bit-identical to the sync train
step on the same batches — the emulator's correctness anchor (tested).

Steady-state staleness under round-robin is ``num_workers - 1``, exactly a
K-worker PS where every worker pushes once per round.  BN moving statistics
follow last-writer-wins, as PS-resident aux variables did.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterable, Mapping, Optional, Sequence

import jax
import numpy as np

from distributed_tensorflow_models_tpu.core import train_loop
from distributed_tensorflow_models_tpu.core.train_loop import LossFn
from distributed_tensorflow_models_tpu.core.train_state import TrainState

PyTree = Any
Batch = Mapping[str, jax.Array]


@dataclasses.dataclass(frozen=True)
class AsyncConfig:
    """Emulation knobs.

    ``schedule``: ``"round_robin"`` (steady staleness K-1) or ``"random"``
    (seeded arrival-order jitter; same seed → same trajectory).
    ``staleness_limit``: drop gradients older than this many canonical
    steps (None = never drop; the reference default — plain async applies
    have no staleness check, only SyncReplicas' accumulators do).
    """

    num_workers: int = 4
    schedule: str = "round_robin"
    seed: int = 0
    staleness_limit: Optional[int] = None


@dataclasses.dataclass
class _Worker:
    params: PyTree
    version: int  # canonical step when this snapshot was fetched


class AsyncPSEmulator:
    """Event-driven async-PS trainer over a compiled grad/apply pair.

    The canonical :class:`TrainState` plays the parameter servers' role
    (single source of truth for params, optimizer slots, BN stats, step);
    virtual workers play the reference's worker processes.
    """

    def __init__(
        self,
        state: TrainState,
        loss_fn: LossFn,
        config: AsyncConfig = AsyncConfig(),
        rng_names: Sequence[str] = ("dropout",),
    ):
        if config.num_workers < 1:
            raise ValueError("need at least one virtual worker")
        self.config = config
        self.state = state
        self._rng_names = tuple(rng_names)
        self.staleness_log: list[int] = []
        self.dropped: int = 0
        self._event = 0
        self._sched_rng = np.random.RandomState(config.seed)
        self.workers = [
            _Worker(params=state.params, version=int(state.step))
            for _ in range(config.num_workers)
        ]

        def grad_fn(params, state, batch, rng, event):
            # Per-event keys via the sync step's own derivation
            # (train_loop.per_step_rngs) so that num_workers=1 replays the
            # sync trajectory exactly — parity by construction, not by
            # copy-paste.
            rngs = train_loop.per_step_rngs(rng, event, self._rng_names)
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, state, batch, rngs
            )
            return grads, aux

        self._grad = jax.jit(grad_fn)
        # Shared state-advance: optimizer update + batch_stats / carry / EMA
        # threading, same code the sync step runs.
        self._apply = jax.jit(train_loop.apply_gradients)

    # -- schedule ----------------------------------------------------------
    def _pick(self) -> int:
        if self.config.schedule == "round_robin":
            return self._event % self.config.num_workers
        if self.config.schedule == "random":
            return int(self._sched_rng.randint(self.config.num_workers))
        raise ValueError(f"unknown schedule {self.config.schedule!r}")

    # -- event loop --------------------------------------------------------
    def step(self, batch: Batch, rng: jax.Array) -> dict:
        """One async event: pick worker → grad at snapshot → apply → fetch.

        Returns the event record (worker id, staleness, dropped flag,
        metrics from the worker's forward pass).
        """
        widx = self._pick()
        worker = self.workers[widx]
        canonical_step = int(self.state.step)
        staleness = canonical_step - worker.version

        grads, aux = self._grad(
            worker.params, self.state, batch, rng, self._event
        )
        dropped = (
            self.config.staleness_limit is not None
            and staleness > self.config.staleness_limit
        )
        if dropped:
            self.dropped += 1
        else:
            self.state = self._apply(self.state, grads, aux)
        # Fetch: worker adopts canonical params (the reference worker's
        # variable read at the top of its next step, SURVEY.md §3.3).
        self.workers[widx] = _Worker(
            params=self.state.params, version=int(self.state.step)
        )
        self.staleness_log.append(staleness)
        self._event += 1
        return {
            "worker": widx,
            "staleness": staleness,
            "dropped": dropped,
            "metrics": aux.get("metrics", {}),
        }

    def run(self, batches: Iterable[Batch], rng: jax.Array) -> list[dict]:
        """Replay a batch stream through the event loop."""
        return [self.step(b, rng) for b in batches]

    @property
    def mean_staleness(self) -> float:
        return float(np.mean(self.staleness_log)) if self.staleness_log else 0.0
