"""Known-good: accelerator imports stay lazy or type-only."""
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    import jax


def supervise():
    import jax

    return jax.device_count()
