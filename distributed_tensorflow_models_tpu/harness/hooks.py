"""Training hooks: the reference's session-hook set, step-callback style.

The reference orchestrates its train loop through ``SessionRunHook``s
(SURVEY.md §2.2 F13; TF basic_session_run_hooks.py): StepCounterHook
(steps/sec), NanTensorHook, StopAtStepHook, LoggingTensorHook,
SummarySaverHook, CheckpointSaverHook.  Here the loop is a plain Python
``for`` over a compiled step, so hooks are simple objects with
``begin/after_step/end`` callbacks — same capabilities, same metric names
and cadences, no graph machinery.

Metric readback note: ``after_step`` receives the *device* metrics dict;
hooks that need host floats call ``float(...)`` themselves, and only on the
steps where they fire, so the hot loop never forces a sync on quiet steps.
"""

from __future__ import annotations

import json
import logging
import os
import time
from collections.abc import MutableMapping
from typing import Any, Iterator, Mapping, Optional, Sequence

import jax
import numpy as np

from distributed_tensorflow_models_tpu import telemetry

log = logging.getLogger("dtm")

Metrics = Mapping[str, Any]


class Hook:
    def begin(self, state) -> None: ...

    def wants_step(self, step: int) -> bool:
        """Does this hook need :meth:`after_step` called at ``step``?

        The fused multi-step loop (``fit`` with ``steps_per_loop > 1``)
        consults this to skip whole hook walks on steps where no hook
        would act — the host-overhead amortisation the fused dispatch
        exists for.  Returning ``True`` is always safe (the unfused loop
        never asks); the default keeps per-step semantics for arbitrary
        user hooks.  Must be cheap, side-effect-free, and — for hooks
        whose ``after_step`` performs a multi-host collective —
        deterministic in ``step`` so every process walks the same rows.
        """
        return True

    def after_step(self, state, metrics: Metrics, step: int) -> None: ...

    def end(self, state) -> None: ...

    def abort(self, state) -> None:
        """Cleanup on the *failure* path.  Defaults to :meth:`end`; hooks
        whose ``end`` performs a multi-host collective must override this —
        a single failing process entering a collective while its peers are
        blocked elsewhere turns a clean per-process error into a
        cluster-wide hang."""
        self.end(state)


class LazyMetricRow(MutableMapping):
    """One step's lazy view into a fused chunk's stacked on-device metrics.

    The fused multi-step program returns every metric as a ``[K]``-stacked
    device array; materialising K host dicts per chunk would reintroduce
    the per-step host cost the fusion removed.  This row adapter indexes a
    leaf only when a hook actually reads the key (the result is still a
    device scalar — only ``float()`` forces the device→host sync), so
    hooks that fire every N steps never sync the other N−1 rows.

    Writes (``TelemetryHook``'s derived-scalar injection) land in a
    host-side overlay that shadows the stacked leaves — the same
    dict-update contract the writer hooks rely on.

    Chunk-aware consumers (``NanGuardHook``) can reach the whole chunk via
    :meth:`stacked` plus :attr:`chunk_start_step`/:attr:`index` to
    attribute a mid-chunk event to its exact step.
    """

    def __init__(self, stacked: Mapping, index: int, chunk_start_step: int):
        self._stacked = stacked
        self._index = index
        self._start = chunk_start_step  # global step of row 0
        self._overlay: dict = {}

    @property
    def index(self) -> int:
        return self._index

    @property
    def chunk_start_step(self) -> int:
        return self._start

    def stacked(self, key: str):
        """The full ``[K]`` device array behind ``key`` (raises KeyError
        for overlay-only keys, which have no per-step history)."""
        return self._stacked[key]

    def __getitem__(self, key):
        if key in self._overlay:
            return self._overlay[key]
        return self._stacked[key][self._index]

    def __setitem__(self, key, value):
        self._overlay[key] = value

    def __delitem__(self, key):
        del self._overlay[key]

    def __iter__(self) -> Iterator[str]:
        yield from self._stacked
        for k in self._overlay:
            if k not in self._stacked:
                yield k

    def __len__(self) -> int:
        return len(set(self._stacked) | set(self._overlay))


class StopRequested(Exception):
    """Raised by hooks to end training (StopAtStepHook's mechanism)."""


class StopAtStepHook(Hook):
    """Stop after ``last_step`` (TF basic_session_run_hooks.py:393)."""

    def __init__(self, last_step: int):
        self._last = last_step

    def wants_step(self, step):
        return step >= self._last

    def after_step(self, state, metrics, step):
        if step >= self._last:
            raise StopRequested


class StepCounterHook(Hook):
    """steps/sec (and examples/sec) every ``every_steps`` — the reference's
    throughput meter (TF basic_session_run_hooks.py:674)."""

    def __init__(self, every_steps: int = 100, batch_size: Optional[int] = None):
        self._every = every_steps
        self._batch = batch_size
        self._t0 = None
        self._s0 = 0
        self.last_steps_per_sec: Optional[float] = None

    def begin(self, state):
        self._t0 = time.perf_counter()
        self._s0 = int(state.step)

    def wants_step(self, step):
        return step % self._every == 0

    def after_step(self, state, metrics, step):
        if step % self._every:
            return
        now = time.perf_counter()
        dt = now - self._t0
        if dt <= 0:
            return
        sps = (step - self._s0) / dt
        self.last_steps_per_sec = sps
        msg = f"step {step}: {sps:.2f} steps/sec"
        if self._batch:
            msg += f", {sps * self._batch:.1f} examples/sec"
        log.info(msg)
        self._t0, self._s0 = now, step


class NanGuardHook(Hook):
    """Abort on non-finite loss (NanTensorHook, TF
    basic_session_run_hooks.py:761).  Checks every ``every_steps`` to avoid
    forcing a device sync each step."""

    def __init__(self, every_steps: int = 100, key: str = "loss"):
        self._every = every_steps
        self._key = key

    def wants_step(self, step):
        return step % self._every == 0

    def after_step(self, state, metrics, step):
        if step % self._every:
            return
        if isinstance(metrics, LazyMetricRow):
            # Fused-chunk row: check EVERY row of the chunk up to this one
            # (one [K]-array readback — same sync cost as the scalar) so a
            # mid-chunk NaN is caught at the boundary walk and attributed
            # to its exact step, not the chunk end.
            arr = np.asarray(metrics.stacked(self._key))[
                : metrics.index + 1
            ]
            bad = ~np.isfinite(arr)
            if bad.any():
                i = int(np.argmax(bad))
                raise FloatingPointError(
                    f"{self._key} is {arr[i]} at step "
                    f"{metrics.chunk_start_step + i}"
                )
            return
        value = float(metrics[self._key])
        if not np.isfinite(value):
            raise FloatingPointError(
                f"{self._key} is {value} at step {step}"
            )


class LoggingHook(Hook):
    """Log scalar metrics every N steps (LoggingTensorHook :169)."""

    def __init__(self, every_steps: int = 100, keys: Optional[Sequence[str]] = None):
        self._every = every_steps
        self._keys = keys

    def wants_step(self, step):
        return step % self._every == 0

    def after_step(self, state, metrics, step):
        if step % self._every:
            return
        keys = self._keys or sorted(metrics)
        parts = []
        for k in keys:
            v = metrics.get(k)
            if v is None:
                continue
            try:
                parts.append(f"{k}={float(v):.4f}")
            except (TypeError, ValueError):
                # Array-valued metric (e.g. a per-class histogram): skip —
                # the same guard SummaryWriter.scalars applies.  Logging
                # must never be the thing that kills training.
                continue
        log.info("step %d: %s", step, ", ".join(parts))


class MetricWriterHook(Hook):
    """Append scalar metrics to ``<workdir>/metrics.jsonl`` every N steps —
    the SummarySaverHook role (TF monitored_session.py:585-590) with a
    dependency-free format (one JSON object per line, TensorBoard-convertible;
    schema documented in README "Observability" and linted by
    ``scripts/check_metrics_schema.py``).

    The file handle stays open across steps (line-buffered append) —
    reopening per write cost a path resolution + fd churn every cadence —
    and each row goes down as ONE ``write`` of the full line, so a
    concurrent ``tail -f`` never sees a torn line."""

    def __init__(self, workdir: str, every_steps: int = 100):
        self._path = os.path.join(workdir, "metrics.jsonl")
        self._every = every_steps
        os.makedirs(workdir, exist_ok=True)
        # buffering=1: text-mode line buffering — flushed to the OS at
        # each newline, i.e. exactly once per row.
        self._f = open(self._path, "a", buffering=1)

    def write_row(self, row: Mapping[str, Any]) -> None:
        """Append one row (atomic single write of the full line)."""
        if self._f.closed:  # post-end() stragglers must not crash
            self._f = open(self._path, "a", buffering=1)
        self._f.write(json.dumps(row) + "\n")

    def wants_step(self, step):
        return step % self._every == 0

    def after_step(self, state, metrics, step):
        if step % self._every:
            return
        row = {"step": step, "time": time.time()}
        for k, v in metrics.items():
            try:
                row[k] = float(v)
            except (TypeError, ValueError):
                continue
        self.write_row(row)

    def end(self, state):
        if not self._f.closed:
            self._f.close()


class TensorBoardHook(Hook):
    """Scalar summaries into TensorBoard event files every ``every_steps``
    (default 100, the reference's SummarySaverHook cadence — TF
    monitored_session.py:517-518), via the no-TF writer in
    :mod:`harness.summary`."""

    def __init__(self, workdir: str, every_steps: int = 100):
        # Chief-only, like the reference's SummarySaverHook (TF
        # monitored_session.py:566-609 chief hooks) — non-zero processes
        # would write duplicate event streams.
        self._writer = None
        if jax.process_index() == 0:
            from distributed_tensorflow_models_tpu.harness.summary import (
                SummaryWriter,
            )

            self._writer = SummaryWriter(
                os.path.join(workdir, "tensorboard")
            )
        self._every = every_steps

    def wants_step(self, step):
        return self._writer is not None and step % self._every == 0

    def after_step(self, state, metrics, step):
        if self._writer is None or step % self._every:
            return
        self._writer.scalars(step, metrics)
        # Flush each write (log-cadence, ~50 bytes): a live TensorBoard
        # sees events immediately and a preemption (SIGKILL skips end())
        # loses nothing buffered.
        self._writer.flush()

    def end(self, state):
        if self._writer is not None:
            self._writer.close()


class TelemetryHook(Hook):
    """Snapshot the telemetry registry every ``every_steps`` and inject the
    derived scalars into the per-step ``metrics`` dict, where the
    downstream writer hooks (MetricWriterHook → ``metrics.jsonl``,
    TensorBoardHook → event files) pick them up on the same cadence.
    **Must be ordered before the writer hooks** (``fit`` does this).

    Injected keys (interval = since the previous cadence firing):

    - ``step_time_s``    — mean full-iteration wall time over the interval
    - ``data_wait_s``    — mean per-step time blocked on the input pipeline
    - ``dispatch_s``     — mean per-step host dispatch time
    - ``steps_per_sec``  — interval throughput
    - ``stall_fraction`` — data-wait share of interval wall time
    - ``mfu``            — FLOPs retired / (interval wall × peak);
      0.0 when the device has no known peak (CPU) or FLOPs are unknown
    - ``compile_count`` / ``compile_s`` — cumulative compile events
    - ``checkpoint_s``   — cumulative blocking checkpoint time (save +
      restore + wait + the overlapped-save durability fence)
    - ``checkpoint/fence_s`` — the fence share alone: wall time saves
      spent blocked on a PREVIOUS async save, i.e. how much tightening
      ``checkpoint_every_steps`` actually costs
    - ``startup/restore_s`` / ``startup/aot_compile_s`` /
      ``startup/time_to_first_step_s`` — the restart-MTTR gauges
      (always the three together — the schema lint checks the set)
    - ``host_queue_depth`` — producer buffer depth right now
    - ``restarts`` / ``rollbacks`` / ``skipped_batches`` — resilience
      counters (recoverable_fit restarts; nan_policy=rollback rewinds
      and the batches their skips discarded)

    Multi-host: steps/sec and stall fraction are allgathered
    (``multihost_utils.process_allgather`` — a collective, so the hook
    must run on EVERY process at the same steps; cadence is
    deterministic in ``step``) and the chief's writers record
    ``hosts/steps_per_sec_{min,mean}`` and ``hosts/stall_fraction_max``
    — one slow or input-bound host is visible without ssh'ing into it.
    """

    def __init__(
        self,
        registry: telemetry.MetricsRegistry,
        every_steps: int = 100,
        process_count: Optional[int] = None,
    ):
        self._reg = registry
        self._every = every_steps
        self._nproc = (
            jax.process_count() if process_count is None else process_count
        )
        try:
            # Whole-mesh peak: the FLOPs numerator is the global SPMD
            # program's cost, so the denominator is per-chip peak x all
            # participating devices (bench.py's global/per-chip split).
            peak = telemetry.peak_flops(jax.devices()[0].device_kind)
            self._peak = peak and peak * len(jax.devices())
        except Exception:  # noqa: BLE001 — telemetry must never crash
            self._peak = None
        self._last: Optional[tuple[float, int, dict]] = None
        self.last_emitted: Optional[dict] = None

    def begin(self, state):
        self._last = (
            time.perf_counter(), int(state.step), self._reg.snapshot()
        )

    def wants_step(self, step):
        # Deterministic in step — required: the multi-host branch of
        # after_step is a collective, so every process must walk the
        # same rows under the fused loop's wants_step gating.
        return step % self._every == 0

    def after_step(self, state, metrics, step):
        if step % self._every:
            return
        now = time.perf_counter()
        snap = self._reg.snapshot()
        t0, s0, prev = self._last or (now, step, {})
        self._last = (now, step, snap)
        d_wall = max(now - t0, 1e-9)
        d_steps = max(step - s0, 0)

        def delta(key: str) -> float:
            return snap.get(key, 0.0) - prev.get(key, 0.0)

        def mean(name: str) -> float:
            n = delta(f"{name}/count")
            return delta(f"{name}/total_s") / n if n else 0.0

        data_wait = delta(f"{telemetry.DATA_WAIT}/total_s")
        sps = d_steps / d_wall
        stall_frac = data_wait / d_wall
        # FLOPs actually retired this interval (signature-exact — mixed
        # batch shapes are each priced at their own program's cost).
        flops_done = delta(telemetry.FLOPS_TOTAL)
        out = {
            "step_time_s": mean(telemetry.STEP_TIME),
            "data_wait_s": data_wait / max(d_steps, 1),
            "dispatch_s": mean(telemetry.DISPATCH),
            "steps_per_sec": sps,
            "stall_fraction": stall_frac,
            "mfu": (
                flops_done / (d_wall * self._peak)
                if self._peak and flops_done > 0
                else 0.0
            ),
            "compile_count": snap.get(f"{telemetry.COMPILE}/count", 0.0),
            "compile_s": snap.get(f"{telemetry.COMPILE}/total_s", 0.0),
            "checkpoint_s": (
                snap.get(f"{telemetry.CKPT_SAVE}/total_s", 0.0)
                + snap.get(f"{telemetry.CKPT_RESTORE}/total_s", 0.0)
                + snap.get(f"{telemetry.CKPT_WAIT}/total_s", 0.0)
                + snap.get(f"{telemetry.CKPT_FENCE}/total_s", 0.0)
            ),
            "checkpoint/fence_s": snap.get(
                f"{telemetry.CKPT_FENCE}/total_s", 0.0
            ),
            "startup/restore_s": snap.get(telemetry.STARTUP_RESTORE, 0.0),
            "startup/aot_compile_s": snap.get(
                telemetry.STARTUP_AOT_COMPILE, 0.0
            ),
            "startup/time_to_first_step_s": snap.get(
                telemetry.STARTUP_FIRST_STEP, 0.0
            ),
            "host_queue_depth": snap.get(telemetry.HOST_QUEUE_DEPTH, 0.0),
            # Resilience counters (always the three together — the schema
            # lint checks them as a set): cumulative within this fit
            # attempt; a recoverable_fit restart resets rollbacks/
            # skipped_batches and bumps restarts (fresh per-run registry,
            # seeded with the attempt count).
            "restarts": snap.get(telemetry.RESTARTS, 0.0),
            "rollbacks": snap.get(telemetry.ROLLBACKS, 0.0),
            "skipped_batches": snap.get(telemetry.SKIPPED_BATCHES, 0.0),
        }
        if self._nproc > 1:
            from jax.experimental import multihost_utils

            gathered = np.asarray(
                multihost_utils.process_allgather(
                    np.asarray([sps, stall_frac], np.float32)
                )
            ).reshape(-1, 2)
            out["hosts/steps_per_sec_min"] = float(gathered[:, 0].min())
            out["hosts/steps_per_sec_mean"] = float(gathered[:, 0].mean())
            out["hosts/stall_fraction_max"] = float(gathered[:, 1].max())
        self.last_emitted = out
        if isinstance(metrics, MutableMapping):
            # dict in the unfused loop, LazyMetricRow (overlay write) in
            # the fused loop — both take the injection for the writer
            # hooks downstream.
            metrics.update(out)


class FleetHook(Hook):
    """Chief-only fleet-health gauges from the heartbeat directory
    (``resilience/heartbeat.py``): every ``every_steps`` it reads the
    peers' heartbeat files — plain shared-filesystem reads, never a
    collective — and injects/records

    - ``fleet/peers_alive``     — processes with a fresh heartbeat,
    - ``fleet/step_lag``        — max−min step among alive peers (the
      straggler / slowest-host skew),
    - ``fleet/heartbeat_age_s`` — the worst heartbeat age,

    into the metrics row (→ metrics.jsonl / TensorBoard via the writer
    hooks downstream — order this before them, like TelemetryHook) and
    the registry (→ telemetry.json).  A dead host shows up here within
    one cadence of its heartbeat going stale, with its process index in
    the chief's log — per-host failure attribution without ssh."""

    def __init__(
        self,
        registry: telemetry.MetricsRegistry,
        directory: str,
        num_processes: int,
        every_steps: int = 100,
        *,
        stale_after_s: float = 15.0,
    ):
        self._reg = registry
        self._dir = directory
        self._nproc = num_processes
        self._every = max(1, every_steps)
        self._stale = stale_after_s
        self._warned_dead: set[int] = set()

    def wants_step(self, step):
        return step % self._every == 0

    def after_step(self, state, metrics, step):
        if step % self._every:
            return
        from distributed_tensorflow_models_tpu.resilience import heartbeat

        try:
            views = heartbeat.read_fleet(self._dir, self._nproc)
            # One snapshot for both the per-peer warnings and the
            # gauges — a second read could classify a peer differently
            # mid-walk.
            summary = heartbeat.fleet_summary(
                self._dir, self._nproc, stale_after_s=self._stale,
                views=views,
            )
        except Exception:  # noqa: BLE001 — telemetry must never kill a run
            log.exception("fleet heartbeat read failed")
            return
        for i, view in enumerate(views):
            stale = view is None or view["age_s"] > self._stale
            if stale and i not in self._warned_dead:
                self._warned_dead.add(i)
                log.warning(
                    "fleet: process %d heartbeat is %s (last step %s)",
                    i,
                    "missing" if view is None else f"{view['age_s']:.1f}s stale",
                    "?" if view is None else view.get("step"),
                )
            elif not stale:
                self._warned_dead.discard(i)
        out = {
            telemetry.FLEET_PEERS_ALIVE: float(summary["peers_alive"]),
            telemetry.FLEET_STEP_LAG: float(summary["step_lag"]),
            telemetry.FLEET_HEARTBEAT_AGE: float(summary["heartbeat_age_s"]),
        }
        for key, value in out.items():
            self._reg.gauge(key).set(value)
        if isinstance(metrics, MutableMapping):
            metrics.update(out)


class CheckpointHook(Hook):
    """Save every ``every_secs`` (default 600 s, the reference's
    CheckpointSaverHook default — TF monitored_session.py:525-528) and at
    ``end``.  ``save_fn(state, step)`` is provided by the driver so the hook
    stays agnostic of checkpoint layout.

    Multi-host: orbax saves are collective, so every process must decide
    "save now" at the *same step*.  A per-process wall clock cannot
    guarantee that (clocks cross the threshold at different steps and the
    early process deadlocks in the save barrier while the others run ahead).
    With ``process_count > 1`` the chief alone reads the clock and its
    decision is broadcast, polled every ``poll_every_steps`` steps to keep
    the collective off the per-step hot path; step-based triggers
    (``every_steps``) are deterministic on every process and need no sync.
    """

    def __init__(self, save_fn, every_secs: float = 600.0,
                 every_steps: Optional[int] = None,
                 poll_every_steps: int = 20):
        self._save = save_fn
        self._every_secs = every_secs
        self._every_steps = every_steps
        self._poll = max(1, poll_every_steps)
        self._last_time = time.time()
        self._multiproc = jax.process_count() > 1

    def _time_due(self, step: int) -> bool:
        if self._every_secs is None:
            return False
        if not self._multiproc:
            return time.time() - self._last_time >= self._every_secs
        if step % self._poll:
            return False
        from jax.experimental import multihost_utils

        chief_due = (
            jax.process_index() == 0
            and time.time() - self._last_time >= self._every_secs
        )
        return bool(
            multihost_utils.broadcast_one_to_all(
                np.asarray(chief_due, np.int32)
            )
        )

    def wants_step(self, step):
        # Step triggers and the multi-host poll cadence are deterministic
        # in step (required — the poll broadcast is a collective); the
        # single-process clock check is local, so reading it here is safe.
        if self._every_steps and step % self._every_steps == 0:
            return True
        if self._every_secs is None:
            return False
        if self._multiproc:
            return step % self._poll == 0
        return time.time() - self._last_time >= self._every_secs

    def after_step(self, state, metrics, step):
        due_step = self._every_steps and step % self._every_steps == 0
        if due_step or self._time_due(step):
            self._save(state, step)
            self._last_time = time.time()

    def end(self, state):
        self._save(state, int(state.step))

    def abort(self, state):
        # Crash-time save is safe (and valuable) single-process; with peers
        # it is a collective this lone failing process must NOT enter — the
        # others are blocked in the next step's all-reduce, not the save
        # barrier.  Recovery then restores the last *scheduled* checkpoint.
        if not self._multiproc:
            self._save(state, int(state.step))
        else:
            log.warning(
                "skipping crash-time checkpoint save on multi-host failure "
                "(collective save cannot run from one process)"
            )


class FaultInjectionHook(Hook):
    """Raise a chosen exception at a chosen step, once.

    The reference has no fault injection anywhere (SURVEY.md §5.3); the
    rebuild adds it as a first-class hook so the recovery path — the
    analogue of ``_RecoverableSession``'s retry loop (TF
    monitored_session.py:1261-1274) — is testable on demand rather than
    only on real preemptions."""

    def __init__(self, step: int, exc_factory=None):
        self._step = step
        self._fired = False
        self._exc_factory = exc_factory or (
            lambda: RuntimeError("injected preemption")
        )

    def wants_step(self, step):
        return step == self._step and not self._fired

    def after_step(self, state, metrics, step):
        if step == self._step and not self._fired:
            self._fired = True
            raise self._exc_factory()


class ProfilerHook(Hook):
    """Capture an XLA/TPU trace for steps [start, stop) into
    ``<workdir>/profile`` — the Timeline/FULL_TRACE replacement (SURVEY.md
    §5.1; TF client/timeline.py:410 → ``jax.profiler``)."""

    def __init__(self, workdir: str, start_step: int, stop_step: int):
        self._dir = os.path.join(workdir, "profile")
        self._start = start_step
        self._stop = stop_step
        self._active = False

    def wants_step(self, step):
        return (not self._active and step == self._start) or (
            self._active and step >= self._stop
        )

    def after_step(self, state, metrics, step):
        if step == self._start and not self._active:
            jax.profiler.start_trace(self._dir)
            self._active = True
        elif step >= self._stop and self._active:
            jax.profiler.stop_trace()
            self._active = False

    def end(self, state):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False


def run_hooks_after_step(hooks: Sequence[Hook], state, metrics, step) -> bool:
    """Returns False when a hook requested stop.  Every hook runs every
    step — a StopRequested from one hook must not starve later hooks of the
    final step's metrics (logging/metric-writer/checkpoint all fire on the
    stop step before the loop exits)."""
    stop = False
    for h in hooks:
        try:
            h.after_step(state, metrics, step)
        except StopRequested:
            stop = True
    return not stop


def run_hooks_after_chunk(
    hooks: Sequence[Hook],
    state,
    stacked_metrics: Mapping,
    start_step: int,
    length: int,
    registry: Optional[telemetry.MetricsRegistry] = None,
    final_row: Optional[LazyMetricRow] = None,
) -> bool:
    """Walk hooks for the ``length`` steps of one fused chunk, skipping
    every step no hook wants (:meth:`Hook.wants_step`) — the K−1 quiet
    steps cost one predicate sweep each, no metric sync, no hook walk.

    The chunk covers steps ``start_step+1 .. start_step+length``; each
    walked step gets a :class:`LazyMetricRow` over ``stacked_metrics``
    (row i ↔ step ``start_step+1+i``).  ``state`` is the end-of-chunk
    state — the only one the fused program materialises; hooks that save
    it (CheckpointHook) therefore always persist chunk-boundary state,
    consistent with the data-position contract of
    ``data/pipeline.py::BatchStacker.get_state``.

    Full walks are counted into ``registry``'s ``train/hook_walks``
    (the micro-guard's numerator).  Per-walk semantics match
    :func:`run_hooks_after_step`: every hook runs, StopRequested defers
    to the end of the walk, and remaining walked steps still run so the
    stop step's metrics reach the writers.

    ``final_row``, when given, is used as the last row's metrics object
    instead of a fresh :class:`LazyMetricRow`, so overlay writes
    (TelemetryHook's injected scalars) are visible to the caller —
    ``fit`` passes the row it returns as ``FitResult.final_metrics``.
    """
    stop = False
    for i in range(length):
        step = start_step + 1 + i
        if not any(h.wants_step(step) for h in hooks):
            continue
        if registry is not None:
            registry.counter(telemetry.HOOK_WALKS).inc()
        if i == length - 1 and final_row is not None:
            row = final_row
        else:
            row = LazyMetricRow(stacked_metrics, i, start_step + 1)
        for h in hooks:
            try:
                h.after_step(state, row, step)
            except StopRequested:
                stop = True
        if stop:
            # Mirror the unfused loop: nothing fires after the stop step
            # (its own walk completed — writers got the final metrics).
            break
    return not stop
