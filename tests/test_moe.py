"""Expert parallelism: the all_to_all EP layout must match the
single-device oracle exactly (same routing, capacity, drops), train, and
balance load via the aux loss."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_models_tpu.core import mesh as meshlib
from distributed_tensorflow_models_tpu.parallel import moe

E_AXIS = 4
NUM_EXPERTS = 8
D_MODEL = 16
D_FF = 32
TOKENS = 64


@pytest.fixture(scope="module")
def expert_mesh():
    return meshlib.create_mesh(meshlib.MeshSpec(data=2, expert=E_AXIS))


@pytest.fixture(scope="module")
def setup():
    params = moe.init_moe_params(
        jax.random.key(0), NUM_EXPERTS, D_MODEL, D_FF
    )
    x = jax.random.normal(jax.random.key(1), (TOKENS, D_MODEL))
    return params, x


def test_ep_matches_single_device_oracle(expert_mesh, setup):
    params, x = setup
    got = jax.jit(
        lambda p, x: moe.moe_ffn(p, x, mesh=expert_mesh)
    )(params, x)
    ref = moe.moe_ffn_reference(params, x, num_ranks=E_AXIS)
    np.testing.assert_allclose(
        np.asarray(got.out), np.asarray(ref.out), atol=1e-5, rtol=1e-5
    )
    np.testing.assert_allclose(
        float(got.aux_loss), float(ref.aux_loss), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(got.dropped_fraction), float(ref.dropped_fraction), atol=1e-6
    )


def test_ep_gradients_match_oracle(expert_mesh, setup):
    params, x = setup

    def loss_ep(p):
        r = moe.moe_ffn(p, x, mesh=expert_mesh)
        return jnp.mean(r.out**2) + 0.01 * r.aux_loss

    def loss_ref(p):
        r = moe.moe_ffn_reference(p, x, num_ranks=E_AXIS)
        return jnp.mean(r.out**2) + 0.01 * r.aux_loss

    g_ep = jax.jit(jax.grad(loss_ep))(params)
    g_ref = jax.jit(jax.grad(loss_ref))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
        ),
        g_ep,
        g_ref,
    )


def test_capacity_drops_tokens(expert_mesh, setup):
    params, x = setup
    tight = jax.jit(
        lambda p, x: moe.moe_ffn(p, x, mesh=expert_mesh, capacity_factor=0.5)
    )(params, x)
    # With top-1 routing and capacity_factor < 1 some tokens must drop
    # (unless routing is perfectly uniform, which random init never is).
    assert float(tight.dropped_fraction) > 0.0
    loose = jax.jit(
        lambda p, x: moe.moe_ffn(p, x, mesh=expert_mesh, capacity_factor=8.0)
    )(params, x)
    assert float(loose.dropped_fraction) == 0.0


def test_moe_trains_and_aux_balances(expert_mesh):
    params = moe.init_moe_params(jax.random.key(2), NUM_EXPERTS, D_MODEL, D_FF)
    x = jax.random.normal(jax.random.key(3), (TOKENS, D_MODEL))
    target = jnp.roll(x, 1, axis=-1) * 0.5

    def loss(p):
        r = moe.moe_ffn(p, x, mesh=expert_mesh, capacity_factor=2.0)
        return jnp.mean((r.out - target) ** 2) + 0.01 * r.aux_loss

    vg = jax.jit(jax.value_and_grad(loss))
    l0 = float(vg(params)[0])
    for _ in range(30):
        l, g = vg(params)
        params = jax.tree.map(lambda p, d: p - 0.5 * d, params, g)
    assert float(vg(params)[0]) < l0 * 0.8


def test_validation_errors(expert_mesh):
    params = moe.init_moe_params(jax.random.key(0), 6, D_MODEL, D_FF)
    x = jnp.zeros((TOKENS, D_MODEL))
    with pytest.raises(ValueError):  # 6 experts % 4 ranks
        moe.moe_ffn(params, x, mesh=expert_mesh)
    params8 = moe.init_moe_params(jax.random.key(0), 8, D_MODEL, D_FF)
    with pytest.raises(ValueError):  # 62 tokens % 4 ranks
        moe.moe_ffn(params8, jnp.zeros((62, D_MODEL)), mesh=expert_mesh)
