"""Training harness: configs, hooks, checkpointing, train/eval drivers.

This package is the L5/L2 replacement (SURVEY.md §1): what the reference
spreads across per-model driver scripts, ``MonitoredTrainingSession`` hook
orchestration (F7/F13), ``Supervisor``/``SessionManager`` bootstrap (F8/F9),
and ``Saver`` checkpointing (F12) collapses into:

- :mod:`config` — one dataclass per reference config [B:6-12];
- :mod:`hooks` — step-callback hooks with the reference's metric names and
  cadences (steps/sec counter, NaN guard, checkpoint/log cadence);
- :mod:`checkpoint` — orbax-backed save/restore of the full training state
  *including input-pipeline position*;
- :mod:`train` — the generic restore-or-init + train-loop driver;
- :mod:`evaluate` — eval loops restoring EMA shadows (SURVEY.md §3.5);
- :mod:`cli` — the command-line entry point replacing the reference's
  per-model ``main()``s and launch scripts (L6).
"""

from distributed_tensorflow_models_tpu.harness.config import (  # noqa: F401
    ExperimentConfig,
    get_config,
    list_configs,
)
