"""Numerics of ops.normalization.BatchNorm vs flax.linen.BatchNorm.

The TPU BatchNorm must be a drop-in for the flax module (same variable
layout, same math in float32) with only dtype discipline changed; these
tests pin that equivalence so model checkpoints stay interchangeable.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_models_tpu.ops.normalization import BatchNorm


def _flax_bn(**kw):
    return nn.BatchNorm(use_fast_variance=True, **kw)


@pytest.fixture
def x32():
    rng = np.random.RandomState(0)
    return jnp.asarray(rng.randn(16, 8, 8, 24).astype(np.float32)) * 3.0 + 1.5


def test_variable_layout_matches_flax(x32):
    ours = BatchNorm(use_running_average=False)
    theirs = _flax_bn(use_running_average=False)
    v_ours = ours.init(jax.random.key(0), x32)
    v_theirs = theirs.init(jax.random.key(0), x32)
    assert jax.tree_util.tree_structure(
        v_ours
    ) == jax.tree_util.tree_structure(v_theirs)


def test_train_mode_matches_flax_f32(x32):
    ours = BatchNorm(use_running_average=False, momentum=0.9)
    theirs = _flax_bn(use_running_average=False, momentum=0.9)
    v = theirs.init(jax.random.key(0), x32)
    y_ours, m_ours = ours.apply(v, x32, mutable=["batch_stats"])
    y_theirs, m_theirs = theirs.apply(v, x32, mutable=["batch_stats"])
    np.testing.assert_allclose(y_ours, y_theirs, atol=1e-4, rtol=1e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4),
        m_ours["batch_stats"],
        m_theirs["batch_stats"],
    )


def test_eval_mode_matches_flax_f32(x32):
    ours = BatchNorm(use_running_average=True)
    theirs = _flax_bn(use_running_average=True)
    v = theirs.init(jax.random.key(0), x32)
    # Non-trivial running stats.
    v = {
        "params": v["params"],
        "batch_stats": {
            "mean": jnp.full((24,), 0.7),
            "var": jnp.full((24,), 2.3),
        },
    }
    np.testing.assert_allclose(
        ours.apply(v, x32), theirs.apply(v, x32), atol=1e-4, rtol=1e-4
    )


def test_bf16_io_keeps_dtype_and_tracks_f32_reference(x32):
    xb = x32.astype(jnp.bfloat16)
    ours = BatchNorm(use_running_average=False)
    v = ours.init(jax.random.key(0), x32)
    y, mut = ours.apply(v, xb, mutable=["batch_stats"])
    assert y.dtype == jnp.bfloat16
    # Stats stay f32 and close to the f32-input reference.
    stats = mut["batch_stats"]
    assert stats["mean"].dtype == jnp.float32
    y32, mut32 = ours.apply(v, x32, mutable=["batch_stats"])
    np.testing.assert_allclose(
        stats["mean"], mut32["batch_stats"]["mean"], atol=0.05, rtol=0.05
    )
    np.testing.assert_allclose(
        np.asarray(y, np.float32), np.asarray(y32), atol=0.1, rtol=0.1
    )


def test_use_running_average_merge_param_contract(x32):
    bn = BatchNorm()  # unspecified at construction, flax-style
    v = bn.init(jax.random.key(0), x32, use_running_average=False)
    # Call-time override works in both directions.
    y_train, _ = bn.apply(
        v, x32, use_running_average=False, mutable=["batch_stats"]
    )
    y_eval = bn.apply(v, x32, use_running_average=True)
    assert not np.allclose(np.asarray(y_train), np.asarray(y_eval))
    # Never specifying it anywhere fails loudly, as in flax.
    with pytest.raises(Exception):
        bn.apply(v, x32)


def test_dtype_kwarg_rejected_loudly():
    with pytest.raises(TypeError):
        BatchNorm(use_running_average=False, dtype=jnp.float32)


def test_axis_name_pmean_matches_global_stats(x32):
    """Under shard_map (per-shard reductions), axis_name must recover the
    same output as unsharded global-batch statistics."""
    from functools import partial

    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    bn_global = BatchNorm(use_running_average=False)
    v = bn_global.init(jax.random.key(0), x32)
    y_ref, m_ref = bn_global.apply(v, x32, mutable=["batch_stats"])

    bn_sharded = BatchNorm(use_running_average=False, axis_name="data")

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=P("data"),
        out_specs=(P("data"), P()),
    )
    def sharded_apply(xs):
        y, m = bn_sharded.apply(v, xs, mutable=["batch_stats"])
        return y, m["batch_stats"]

    y_sh, stats_sh = sharded_apply(x32)
    np.testing.assert_allclose(
        np.asarray(y_sh), np.asarray(y_ref), atol=1e-5, rtol=1e-5
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5
        ),
        stats_sh,
        m_ref["batch_stats"],
    )


def test_scale_init_zero_gives_pure_bias():
    x = jnp.ones((4, 3, 3, 5))
    bn = BatchNorm(
        use_running_average=False, scale_init=nn.initializers.zeros
    )
    v = bn.init(jax.random.key(0), x)
    y, _ = bn.apply(v, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y), 0.0, atol=1e-6)
