"""Multi-process launcher — the L6 layer, TPU-native form.

The reference's outermost layer is per-model shell scripts that spawn N
``ps`` + M ``worker`` Python processes across hosts, passing ``--job_name``
and ``--task_index`` flags that each driver turns into a ``ClusterSpec`` +
``tf.train.Server`` (SURVEY.md §1 L6, §2.1 R1; TF training/server_lib.py:
96,107-146,242).  There is no resource manager — placement is manual.

The SPMD equivalent is radically smaller: every process runs the *same*
program; the only per-process facts are ``(coordinator_address,
num_processes, process_id)``, wired into ``jax.distributed.initialize``
(control plane only — the data plane is compiled XLA collectives over
ICI/DCN, SURVEY.md §5.8).  This module provides:

- the ``DTM_*`` environment convention carrying those three facts
  (the analogue of R1's ``--job_name/--task_index`` flags),
- :func:`initialize_from_env` — process-side bootstrap,
- :func:`launch_local` — spawn an N-process cluster on localhost
  (the analogue of TF's in-process fake clusters via
  ``Server.create_local_server``, SURVEY.md §4: multi-node protocol tests
  on one machine with no real cluster),
- a CLI: ``python -m distributed_tensorflow_models_tpu.launch``.

On managed TPU slices none of this is needed — ``jax.distributed
.initialize()`` auto-detects the slice topology and each host runs the same
command; use the CLI only for manual clusters and localhost tests.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import Mapping, Sequence

ENV_COORDINATOR = "DTM_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "DTM_NUM_PROCESSES"
ENV_PROCESS_ID = "DTM_PROCESS_ID"
ENV_CPU_DEVICES = "DTM_CPU_DEVICES_PER_PROCESS"

DEFAULT_PORT = 9671

# Exit code a preempted-but-checkpointed training process uses (BSD
# EX_TEMPFAIL): the run wrote an emergency checkpoint on SIGTERM and
# rerunning the same command resumes it.  ``launch_local`` reports such
# children as resumable instead of replaying their logs as a failure,
# and propagates the code so outer supervisors can requeue.
RESUMABLE_EXIT_CODE = 75


def aggregate_exit_codes(codes) -> int:
    """Cluster exit code: a real failure always wins over "preempted"
    (one resumable child must not relabel another child's crash as
    resumable), preempted wins over success, all-zero is success."""
    failures = [c for c in codes if c not in (0, RESUMABLE_EXIT_CODE)]
    if failures:
        return max(failures)
    if RESUMABLE_EXIT_CODE in codes:
        return RESUMABLE_EXIT_CODE
    return 0


def initialize_from_env() -> bool:
    """Bootstrap ``jax.distributed`` from ``DTM_*`` env vars.

    Returns True if a multi-process cluster was configured, False when the
    env carries no cluster facts (single-process mode — the common case, and
    the analogue of running a reference driver without ``--job_name``).

    Must run before first backend use.  When ``DTM_CPU_DEVICES_PER_PROCESS``
    is set the process is forced onto that many fake CPU devices first
    (test clusters, SURVEY.md §4.3) and gloo cross-process collectives are
    enabled so psum/all-gather actually cross process boundaries.
    """
    cpu_devices = os.environ.get(ENV_CPU_DEVICES)
    if cpu_devices:
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={cpu_devices}"
        if "xla_force_host_platform_device_count" in flags:
            # Replace an inherited count (e.g. the test conftest's 8).
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", want, flags
            )
        else:
            flags = f"{flags} {want}".strip()
        os.environ["XLA_FLAGS"] = flags
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")

    coord = os.environ.get(ENV_COORDINATOR)
    nproc = os.environ.get(ENV_NUM_PROCESSES)
    pid = os.environ.get(ENV_PROCESS_ID)
    if not (coord and nproc and pid):
        return False

    from distributed_tensorflow_models_tpu.core.mesh import (
        initialize_multihost,
    )

    initialize_multihost(
        coordinator_address=coord,
        num_processes=int(nproc),
        process_id=int(pid),
    )
    return True


def launch_local(
    num_processes: int,
    argv: Sequence[str],
    *,
    port: int = DEFAULT_PORT,
    cpu_devices_per_process: int | None = None,
    extra_env: Mapping[str, str] | None = None,
    timeout: float | None = None,
) -> list[int]:
    """Spawn ``num_processes`` copies of ``argv`` as a localhost cluster.

    Each child gets the ``DTM_*`` cluster facts in its environment; process
    0's stdout/stderr pass through, the rest stream into temp files and are
    replayed only on failure (mirroring the reference launch scripts'
    per-task logs, R1).  Files, not pipes: a sequentially-drained pipe
    back-pressures a chatty child into blocking mid-step, which stalls the
    whole cluster at its next collective.  ``timeout`` bounds the *total*
    wall time of the cluster, not each child.  Returns the exit codes.
    """
    import tempfile
    import time

    procs: list[subprocess.Popen] = []
    logs: list = [None]
    try:
        for i in range(num_processes):
            env = dict(os.environ)
            env[ENV_COORDINATOR] = f"127.0.0.1:{port}"
            env[ENV_NUM_PROCESSES] = str(num_processes)
            env[ENV_PROCESS_ID] = str(i)
            if cpu_devices_per_process is not None:
                env[ENV_CPU_DEVICES] = str(cpu_devices_per_process)
            if extra_env:
                env.update(extra_env)
            log = None
            if i != 0:
                log = tempfile.TemporaryFile(
                    mode="w+", prefix=f"dtm-launch-{i}-"
                )
                logs.append(log)
            procs.append(
                subprocess.Popen(
                    list(argv),
                    env=env,
                    stdout=None if i == 0 else log,
                    stderr=None if i == 0 else subprocess.STDOUT,
                )
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        codes = []
        for i, p in enumerate(procs):
            remaining = (
                None if deadline is None else deadline - time.monotonic()
            )
            if remaining is not None and remaining <= 0:
                raise subprocess.TimeoutExpired(argv, timeout)
            p.wait(timeout=remaining)
            codes.append(p.returncode)
            if p.returncode == RESUMABLE_EXIT_CODE:
                # Preemption grace, not a failure: the child checkpointed
                # and asked to be rerun — don't dump its log as a crash.
                sys.stderr.write(
                    f"--- process {i} preempted (exit {p.returncode}): "
                    "resumable — rerun the same command ---\n"
                )
            elif p.returncode != 0 and i != 0:
                logs[i].seek(0)
                sys.stderr.write(
                    f"--- process {i} (exit {p.returncode}) ---\n"
                    f"{logs[i].read()}\n"
                )
        return codes
    except BaseException:
        for p in procs:
            if p.poll() is None:
                p.kill()
        raise
    finally:
        for log in logs:
            if log is not None:
                log.close()


def main(argv: Sequence[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distributed_tensorflow_models_tpu.launch",
        description=(
            "Launch a command as an N-process jax.distributed cluster. "
            "Localhost mode spawns all processes; multi-host mode "
            "(--process-id given) configures this process only — run the "
            "same command on every host with its own --process-id, like "
            "the reference's per-host launch scripts."
        ),
    )
    parser.add_argument("--num-processes", type=int, required=True)
    parser.add_argument(
        "--coordinator",
        default=f"127.0.0.1:{DEFAULT_PORT}",
        help="host:port of process 0's coordination service",
    )
    parser.add_argument(
        "--process-id",
        type=int,
        default=None,
        help="multi-host mode: this host's process index; omit for "
        "localhost mode (spawns all processes here)",
    )
    parser.add_argument(
        "--cpu-devices-per-process",
        type=int,
        default=None,
        help="force N fake CPU devices per process (test clusters)",
    )
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (append: -- python your_driver.py)")

    host, sep, port_str = args.coordinator.rpartition(":")
    if not sep or not port_str.isdigit():
        parser.error(
            f"--coordinator must be host:port, got {args.coordinator!r}"
        )

    if args.process_id is None:
        if host not in ("127.0.0.1", "localhost"):
            parser.error(
                "localhost mode spawns every process here; a non-local "
                f"--coordinator host ({host!r}) requires --process-id "
                "(run once per host)"
            )
        codes = launch_local(
            args.num_processes,
            command,
            port=int(port_str),
            cpu_devices_per_process=args.cpu_devices_per_process,
        )
        return aggregate_exit_codes(codes)

    env = os.environ
    env[ENV_COORDINATOR] = args.coordinator
    env[ENV_NUM_PROCESSES] = str(args.num_processes)
    env[ENV_PROCESS_ID] = str(args.process_id)
    if args.cpu_devices_per_process is not None:
        env[ENV_CPU_DEVICES] = str(args.cpu_devices_per_process)
    os.execvp(command[0], command)


if __name__ == "__main__":
    sys.exit(main())
