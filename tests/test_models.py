"""Model-zoo golden-shape and parameter-count tests (SURVEY.md §4.1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_models_tpu.models import (
    available_models,
    get_model,
)


def n_params(tree):
    return sum(x.size for x in jax.tree.leaves(tree))


def init_shapes(model, sample, **kwargs):
    """eval_shape init: no FLOPs, runs the biggest models on CPU instantly."""
    return jax.eval_shape(
        lambda rng: model.init(rng, sample, **kwargs), jax.random.key(0)
    )


def test_registry_complete():
    # The reference zoo, SURVEY.md §2.1 R3-R8.
    for name in [
        "lenet",
        "resnet32_cifar",
        "resnet50",
        "inception_v3",
        "vgg16",
        "alexnet",
        "ptb_lstm",
    ]:
        assert name in available_models(), name


def test_lenet_forward():
    model = get_model("lenet")
    variables = model.init(jax.random.key(0), jnp.zeros((2, 28, 28, 1)))
    out = model.apply(variables, jnp.zeros((2, 28, 28, 1)))
    assert out.shape == (2, 10)
    # conv(5*5*1*32+32) + conv(5*5*32*64+64) + fc(3136*1024+1024) + fc(1024*10+10)
    assert n_params(variables["params"]) == 3_274_634


def test_resnet32_cifar():
    model = get_model("resnet32_cifar")
    variables = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    out = model.apply(variables, jnp.zeros((2, 32, 32, 3)))
    assert out.shape == (2, 10)
    # ResNet-32 is ~0.46M params (He et al.); projection shortcuts add a bit.
    count = n_params(variables["params"])
    assert 4.4e5 < count < 5.5e5, count
    # 3 stages x 5 blocks x 2 convs + init conv + head = 32 conv/fc layers
    bn_state = variables["batch_stats"]
    assert len(jax.tree.leaves(bn_state)) > 0


def test_resnet50_shapes():
    model = get_model("resnet50", dtype=jnp.float32)
    shapes = init_shapes(model, jnp.zeros((1, 224, 224, 3)))
    count = n_params(shapes["params"])
    # torchvision resnet50: 25,557,032.
    assert 25.0e6 < count < 26.0e6, count


def test_resnet50_tiny_forward():
    # Real forward at 32x32 to exercise the graph cheaply.
    model = get_model("resnet50", num_classes=7, dtype=jnp.float32)
    variables = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    out = model.apply(variables, jnp.zeros((2, 32, 32, 3)))
    assert out.shape == (2, 7)


def test_inception_v3_shapes():
    model = get_model("inception_v3", dtype=jnp.float32)
    shapes = jax.eval_shape(
        lambda rng: model.init(rng, jnp.zeros((1, 299, 299, 3)), train=False),
        jax.random.key(0),
    )
    count = n_params(shapes["params"])
    # torchvision inception_v3 with aux: ~27.2M.  Aux params are declared
    # at init regardless of mode (the harness inits with train=False and
    # trains with train=True).
    assert 26e6 < count < 28.5e6, count


def test_inception_v3_train_returns_aux():
    model = get_model("inception_v3", num_classes=5, dtype=jnp.float32)
    x = jnp.zeros((1, 299, 299, 3))
    shapes = jax.eval_shape(
        lambda rng: model.init(rng, x, train=True), jax.random.key(0)
    )
    out_shapes = jax.eval_shape(
        lambda v: model.apply(
            v, x, train=True,
            rngs={"dropout": jax.random.key(1)},
            mutable=["batch_stats"],
        ),
        shapes,
    )
    (logits, aux), _ = out_shapes
    assert logits.shape == (1, 5)
    assert aux.shape == (1, 5)


def test_resnet50_param_count():
    model = get_model("resnet50", dtype=jnp.float32)
    shapes = jax.eval_shape(
        lambda rng: model.init(rng, jnp.zeros((1, 224, 224, 3))),
        jax.random.key(0),
    )
    count = n_params(shapes["params"])
    # Canonical ResNet-50 v1: 25,557,032 (conv/fc weights + BN affine).
    assert abs(count - 25_557_032) / 25_557_032 < 0.01, count


def test_vgg16_param_count():
    model = get_model("vgg16", dtype=jnp.float32)
    shapes = jax.eval_shape(
        lambda rng: model.init(rng, jnp.zeros((1, 224, 224, 3))),
        jax.random.key(0),
    )
    count = n_params(shapes["params"])
    # Classic VGG-16: 138,357,544.
    assert abs(count - 138_357_544) / 138_357_544 < 0.01, count


def test_alexnet_forward_shape():
    model = get_model("alexnet", num_classes=11, dtype=jnp.float32)
    shapes = jax.eval_shape(
        lambda rng: model.init(rng, jnp.zeros((1, 224, 224, 3))),
        jax.random.key(0),
    )
    out = jax.eval_shape(
        lambda v: model.apply(v, jnp.zeros((3, 224, 224, 3))), shapes
    )
    assert out.shape == (3, 11)


class TestPTBLSTM:
    def test_forward_and_carry(self):
        model = get_model("ptb_lstm", config="small", vocab_size=100)
        tokens = jnp.zeros((4, 8), jnp.int32)
        variables = model.init(jax.random.key(0), tokens)
        (logits, carry) = model.apply(variables, tokens)
        assert logits.shape == (4, 8, 100)
        assert len(carry) == model.num_layers
        c, h = carry[0]
        assert c.shape == (4, model.hidden_size)

    def test_carry_threads_state(self):
        """The reference threads final LSTM state into the next segment
        (SURVEY.md §7.4.5): same tokens with different carries must differ."""
        model = get_model("ptb_lstm", config="small", vocab_size=50)
        tokens = jnp.ones((2, 4), jnp.int32)
        variables = model.init(jax.random.key(0), tokens)
        logits1, carry1 = model.apply(variables, tokens)
        logits2, _ = model.apply(variables, tokens, carry=carry1)
        assert not np.allclose(logits1, logits2)

    def test_configs(self):
        from distributed_tensorflow_models_tpu.models.ptb_lstm import (
            PTB_CONFIGS,
        )
        assert set(PTB_CONFIGS) == {"small", "medium", "large"}
        assert PTB_CONFIGS["medium"]["hidden_size"] == 650

    def test_lstm_tp_rules_cover_params(self):
        """Every lstm_tp rule must match at least one parameter path —
        this file's fused-gate rename is exactly the kind of change that
        silently voids a rule set (the old per-gate regex matched
        nothing after it)."""
        import re

        from distributed_tensorflow_models_tpu.core.sharding import (
            _path_str,
        )
        from distributed_tensorflow_models_tpu.parallel import (
            tensor as tensorlib,
        )

        model = get_model("ptb_lstm", config="small")
        variables = jax.eval_shape(
            lambda rng: model.init(
                rng, jnp.zeros((2, 4), jnp.int32), model.initial_carry(2)
            ),
            jax.random.key(0),
        )
        paths = [
            _path_str(p)
            for p, _ in jax.tree_util.tree_leaves_with_path(
                variables["params"]
            )
        ]
        for pattern, _ in tensorlib.lstm_tp_rules():
            assert any(re.search(pattern, p) for p in paths), pattern

    def test_fused_cell_matches_flax_lstm(self):
        """The hoisted-input fused-gate layer == flax's per-gate
        OptimizedLSTMCell stepped over time, on mapped parameters —
        pins the gate order (i|f|g|o) and the recurrence math of the
        cuDNN-style decomposition."""
        import flax.linen as fnn

        from distributed_tensorflow_models_tpu.models.ptb_lstm import (
            _RecurrentCore,
        )

        h = 16
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(3, 7, h).astype(np.float32))
        c0 = jnp.asarray(rng.randn(3, h).astype(np.float32))
        h0 = jnp.asarray(rng.randn(3, h).astype(np.float32))

        ih = fnn.Dense(4 * h, name="ih")
        ihp = ih.init(jax.random.key(1), x)["params"]
        core = _RecurrentCore(h, jnp.float32)
        corep = core.init(
            jax.random.key(2), (c0, h0), jnp.zeros((3, 4 * h))
        )["params"]

        gx = ih.apply({"params": ihp}, x)
        carry = (c0, h0)
        fused_out = []
        for t in range(7):
            carry, ht = core.apply({"params": corep}, carry, gx[:, t])
            fused_out.append(ht)

        # Map fused [in,4h] (i|f|g|o) onto the per-gate flax cell.
        cell = fnn.OptimizedLSTMCell(h)
        Wih = ihp["kernel"].reshape(h, 4, h)
        bih = ihp["bias"].reshape(4, h)
        Whh = corep["hh"]["kernel"].reshape(h, 4, h)
        gates = ["i", "f", "g", "o"]
        flax_params = {}
        for gi, gname in enumerate(gates):
            flax_params[f"i{gname}"] = {"kernel": Wih[:, gi]}
            flax_params[f"h{gname}"] = {
                "kernel": Whh[:, gi],
                "bias": bih[gi],
            }
        carry = (c0, h0)
        ref_out = []
        for t in range(7):
            carry, ht = cell.apply(
                {"params": flax_params}, carry, x[:, t]
            )
            ref_out.append(ht)
        np.testing.assert_allclose(
            np.stack(fused_out, 1), np.stack(ref_out, 1),
            rtol=1e-5, atol=1e-5,
        )


# --------------------------------------------------------------------------
# Inception-v3 architecture oracle vs tf_keras (VERDICT r1 item 7)
# --------------------------------------------------------------------------


class TestInceptionV3KerasOracle:
    """Pin the layer schedule against an independent implementation:
    ``tf_keras.applications.InceptionV3`` builds the same Szegedy et al.
    architecture the reference's slim builder does.  Shape tests can't
    catch a transposed branch width (e.g. swapping Mixed_6b's 128-wide
    factorized-7x7 branch with Mixed_6e's 192) — the conv-kernel multiset
    comparison here does.

    Documented deliberate divergences from keras/slim:
    - our ``BatchNorm`` keeps a trainable ``scale`` (gamma); keras
      applications and slim's inception arg_scope use ``scale=False``.
      Accounted for exactly in the param-count assertion.
    - the aux head (``aux_head=True``) exists in slim but not in keras
      applications; compared with ``aux_head=False``.
    """

    @pytest.fixture(scope="class")
    def keras_model(self):
        tf_keras = pytest.importorskip("tf_keras")
        return tf_keras.applications.InceptionV3(
            weights=None, include_top=True, classes=1000
        )

    @pytest.fixture(scope="class")
    def our_variables(self):
        model = get_model("inception_v3", aux_head=False)
        return init_shapes(model, jnp.zeros((1, 299, 299, 3), jnp.float32))

    def _our_leaves(self, variables):
        return jax.tree_util.tree_leaves_with_path(variables["params"])

    def test_conv_kernel_multiset_matches(self, keras_model, our_variables):
        import tf_keras

        ref = sorted(
            tuple(int(d) for d in layer.kernel.shape)
            for layer in keras_model.layers
            if isinstance(layer, tf_keras.layers.Conv2D)
        )
        ours = sorted(
            tuple(leaf.shape)
            for path, leaf in self._our_leaves(our_variables)
            if path[-1].key == "kernel" and len(leaf.shape) == 4
        )
        assert len(ours) == len(ref) == 94
        assert ours == ref

    def test_dense_head_matches(self, keras_model, our_variables):
        import tf_keras

        (ref_dense,) = [
            tuple(int(d) for d in layer.kernel.shape)
            for layer in keras_model.layers
            if isinstance(layer, tf_keras.layers.Dense)
        ]
        (our_dense,) = [
            tuple(leaf.shape)
            for path, leaf in self._our_leaves(our_variables)
            if path[-1].key == "kernel" and len(leaf.shape) == 2
        ]
        assert our_dense == ref_dense == (2048, 1000)

    def test_param_count_matches_modulo_bn_scale(
        self, keras_model, our_variables
    ):
        ref_total = keras_model.count_params()
        our_total = n_params(our_variables["params"]) + n_params(
            our_variables["batch_stats"]
        )
        # Our one deliberate divergence: a trainable gamma per BN feature.
        gammas = sum(
            leaf.size
            for path, leaf in self._our_leaves(our_variables)
            if path[-1].key == "scale"
        )
        assert gammas > 0
        assert our_total - gammas == ref_total
