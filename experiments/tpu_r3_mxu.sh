#!/bin/bash
# Chained round-3 runner: banks the Pallas implicit-GEMM (impl=mxu) conv
# benches AFTER the main priority ladder (tpu_r3_run.sh) completes, and
# only THEN re-arms and runs the native-conv ladder — the one program
# class that historically wedges the relay, so it stays dead last across
# both runners (the deferral sentinel in conv_ladder.py parks the main
# runner's attempt).
set -u
cd "$(dirname "$0")/.."
LOG=experiments/tpu_recovery.log
R=r3-mxu

echo "$(date) [$R] waiting for main runner" >> "$LOG"
while [ ! -f /tmp/tpu_r3_done ]; do sleep 60; done
echo "$(date) [$R] main runner done; starting mxu benches" >> "$LOG"

bench_one() {  # name outfile [extra bench args...]
    local name="$1" out="$2"; shift 2
    echo "$(date) [$R] bench $name -> $out $*" >> "$LOG"
    DTM_CONV_IMPL=mxu timeout 1500 python bench.py --config "$name" \
        --no-probe "$@" > "experiments/$out" 2>> "$LOG"
    local rc=$?
    echo "$(date) [$R] bench $name rc=$rc $(tail -c 300 "experiments/$out" 2>/dev/null)" >> "$LOG"
    return $rc
}

# Headliner first, best-known batches first so something banks early.
for b in 128 256 64; do
    bench_one resnet50 "tpu_r3_mxu_resnet50_b${b}.json" --batch "$b"
done
for b in 64 128; do
    bench_one inception_v3 "tpu_r3_mxu_inception_b${b}.json" --batch "$b"
done
bench_one resnet32 "tpu_r3_mxu_resnet32.json"
bench_one vgg16 "tpu_r3_mxu_vgg16.json"
bench_one alexnet "tpu_r3_mxu_alexnet.json"
bench_one lenet "tpu_r3_mxu_lenet.json"

# Native conv ladder: re-arm and run, still dead last.
echo "$(date) [$R] native conv ladder (re-armed)" >> "$LOG"
rm -f /tmp/dtm_defer_native_ladder
DTM_CONV_IMPL=xla python experiments/conv_ladder.py --timeout 420 \
    --out experiments/conv_ladder_r3.json >> "$LOG" 2>&1
echo "$(date) [$R] native conv ladder rc=$?" >> "$LOG"

echo "$(date) [$R] runner DONE" >> "$LOG"
touch /tmp/tpu_r3_mxu_done
