"""Rotary position embeddings (RoPE, Su et al.) — the relative-position
encoding used by modern decoder LMs in place of learned absolute tables.

Position enters attention by rotating each (even, odd) feature pair of q
and k by an angle proportional to the token's GLOBAL position, so the
q·k dot product depends only on relative distance.  Properties this
module's consumers rely on:

- Decode: keys are cached post-rotation, so a cached key never needs
  re-rotating as the query advances (the standard KV-cache convention);
  queries rotate by their own absolute position (the cache index).
- Sequence parallelism: rotation is position-elementwise, so each ring
  device rotates its local chunk by its global positions before the KV
  chunks start traveling — no cross-device coordination.
- Kernels: rotation happens before the attention call; flash/blockwise
  see ordinary q/k and need no RoPE awareness.

Half-split ("rotate_half", GPT-NeoX/Llama) convention: features [0, D/2)
pair with [D/2, D).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_angles(positions: jax.Array, dim: int, theta: float = 10000.0):
    """Cos/sin tables for ``positions`` (any int shape) and head dim
    ``dim`` (must be even).  Returns f32 ``(..., dim/2)`` pairs."""
    if dim % 2:
        raise ValueError(f"RoPE head dim must be even, got {dim}")
    inv_freq = theta ** (
        -jnp.arange(0, dim, 2, dtype=jnp.float32) / dim
    )  # [dim/2]
    ang = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(
    x: jax.Array,
    positions: jax.Array,
    theta: float = 10000.0,
) -> jax.Array:
    """Rotate ``x [B, T, H, D]`` by its tokens' global ``positions``
    (shape ``[T]`` or ``[B, T]``).  Rotation in f32, result cast back to
    the input dtype (bf16 activations rotate without accumulating
    round-off into the angle math)."""
    B, T, H, D = x.shape
    cos, sin = rope_angles(positions, D, theta)  # [..., T, D/2]
    # Broadcast to [B, T, 1, D/2] over heads.
    if cos.ndim == 2:
        cos, sin = cos[None], sin[None]
    cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., : D // 2], x32[..., D // 2 :]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)
