"""Core runtime: mesh construction, sharding rules, train state, train loop."""

from distributed_tensorflow_models_tpu.core import mesh
from distributed_tensorflow_models_tpu.core import sharding
from distributed_tensorflow_models_tpu.core.mesh import (
    AxisNames,
    MeshSpec,
    create_mesh,
)
from distributed_tensorflow_models_tpu.core.train_state import TrainState
