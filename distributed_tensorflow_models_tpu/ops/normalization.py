"""TPU-tuned batch normalization.

Drop-in replacement for ``flax.linen.BatchNorm`` used by every conv model in
the zoo (reference semantics: slim's conv+BN arg_scope and the CIFAR ResNet
tutorial BN — SURVEY.md §2.1 R4-R7).  Differences from the flax module are
purely about dtype discipline on TPU:

- The elementwise normalize/scale/shift path runs in the *input* dtype
  (bfloat16 in the zoo's training configs).  flax's ``BatchNorm`` with
  ``dtype=float32`` promotes the activation tensor to float32, which doubles
  HBM read+write traffic on what is a bandwidth-bound op; measured on this
  repo's ResNet-50 bench that costs ~24% of end-to-end training throughput
  (see bench.py).
- Statistics are always *accumulated* in float32 regardless of input dtype
  (a bfloat16 ``E[x^2] - E[x]^2`` would be numerically catastrophic), and the
  per-channel affine constants are folded in float32 down to one fused
  multiply-add in the activation dtype:  ``y = x * a + b`` with
  ``a = scale / sqrt(var + eps)`` and ``b = bias - mean * a``.

Parameter/collection layout is identical to ``flax.linen.BatchNorm``
(params ``scale``/``bias``; batch_stats ``mean``/``var``, biased variance),
so checkpoints and model code are interchangeable between the two.

Under ``jit`` with a batch-sharded input the statistics reductions are
*global* across the mesh automatically (XLA inserts the cross-chip psum) —
sync BN, the documented divergence from the reference's per-replica BN
(SURVEY.md §7.4.2).  Under ``shard_map``/``pmap``, where reductions are
per-shard, pass ``axis_name`` to restore the same global semantics.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


class BatchNorm(nn.Module):
    """Batch normalization with bf16-friendly I/O and float32 statistics.

    Attributes:
      use_running_average: eval mode — normalize with the stored running
        statistics instead of batch statistics.  As in flax, it may be left
        ``None`` at construction and supplied at call time; leaving it
        unspecified in both places is an error.
      momentum: running-statistics decay (slim inception uses 0.9997, the
        CIFAR/ResNet tutorials 0.9 — SURVEY.md §2.1 R4/R5).
      epsilon: numerical floor inside the rsqrt.
      axis_name: optional mapped axis to ``pmean`` statistics over (only
        needed under shard_map/pmap; under jit global-batch semantics are
        automatic).
      scale_init/bias_init: parameter initializers (zero ``scale_init`` is
        the ResNet last-BN identity-start trick).

    Unlike ``flax.linen.BatchNorm`` there is no ``dtype`` attribute: the
    elementwise path always runs in the *input* dtype and statistics always
    accumulate in float32, so a dtype knob would either lie or reintroduce
    the f32 activation round-trip this module exists to remove.  Passing
    ``dtype=`` raises a ``TypeError`` at construction — loud, not silent.
    """

    use_running_average: Optional[bool] = None
    momentum: float = 0.9
    epsilon: float = 1e-5
    axis_name: Optional[str] = None
    scale_init: nn.initializers.Initializer = nn.initializers.ones
    bias_init: nn.initializers.Initializer = nn.initializers.zeros

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        use_running_average: Optional[bool] = None,
    ) -> jax.Array:
        use_running_average = nn.merge_param(
            "use_running_average",
            self.use_running_average,
            use_running_average,
        )
        features = x.shape[-1]
        reduce_axes = tuple(range(x.ndim - 1))

        scale = self.param(
            "scale", self.scale_init, (features,), jnp.float32
        )
        bias = self.param(
            "bias", self.bias_init, (features,), jnp.float32
        )
        ra_mean = self.variable(
            "batch_stats",
            "mean",
            lambda *a: jnp.zeros(*a, jnp.float32),
            (features,),
        )
        ra_var = self.variable(
            "batch_stats",
            "var",
            lambda *a: jnp.ones(*a, jnp.float32),
            (features,),
        )

        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            xf = x.astype(jnp.float32)
            # Two sibling reductions over the same operand — XLA multi-output
            # fusion reads x once (bf16) and accumulates both in f32.
            mean = jnp.mean(xf, reduce_axes)
            mean_sq = jnp.mean(jnp.square(xf), reduce_axes)
            if self.axis_name is not None:
                mean, mean_sq = lax.pmean((mean, mean_sq), self.axis_name)
            var = jnp.maximum(mean_sq - jnp.square(mean), 0.0)
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1.0 - m) * mean
                ra_var.value = m * ra_var.value + (1.0 - m) * var

        inv = lax.rsqrt(var + self.epsilon) * scale
        shift = bias - mean * inv
        # One fused multiply-add in the activation dtype.
        return x * inv.astype(x.dtype) + shift.astype(x.dtype)
