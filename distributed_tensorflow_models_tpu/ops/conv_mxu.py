"""Implicit-GEMM 2-D convolution as a Pallas TPU kernel.

Why a third lowering exists (beside ``impl="xla"`` and ``impl="patches"``,
ops/conv.py): the conv models are the reference's headline benchmarks
(SURVEY.md §2.1 R3-R7) and on this machine the only relay-viable HLO class
is matmul-shaped programs (experiments/TPU_BENCH_r2.md).  ``patches`` is in
that class but materializes the im2col tensor — a kh*kw-fold HBM blow-up
that caps ResNet-50 near 4% MFU (experiments/tpu_r3_resnet50_b*.json).
This module computes the same contraction *inside* a Pallas kernel: the
input tile is DMA'd to VMEM once, the kh*kw shifted windows are read from
VMEM (free), and the only HBM traffic is one read of x, one read of the
kernel per output-channel tile, and one write of y — the implicit-GEMM
scheme every native conv engine uses on a systolic array, here built
directly on the MXU.

Structure:

- ``_core`` — stride-1 VALID conv ``[B,Hp,Wp,Cin] x [kh,kw,Cin,Cout]``,
  the only Pallas entry point.  Grid ``(B/bb, OH/boh, Cout/bco)``; each
  step manually DMAs a ``[bb, boh+kh-1, Wp, Cin]`` halo slab (overlapping
  row windows are inexpressible as BlockSpec tiles), then accumulates
  kh*kw MXU matmuls ``[bb*boh*OW, Cin] @ [Cin, bco]`` in f32.
- strides are decomposed OUTSIDE the kernel into a sum of s_h*s_w
  decimated stride-1 convs (``y = sum_pq core(x[p::s, q::s], k[p::s,
  q::s])``) — exact, zero wasted FLOPs, and the surrounding HLO is only
  strided-slice/pad/add (relay-safe).
- 1x1 convs skip Pallas entirely: after decimation they ARE a single
  ``dot_general`` (the patches 1x1 path, which has no blow-up).
- low-utilization input channels fall back to ``patches``: the kernel's
  explicit cin→128 lane pad makes the MXU contraction pay
  ``ceil(cin/128)·128/cin``× zero-column MACs, so routing is by estimated
  lane utilization (``_use_mxu_kernel``; < 50% → patches, whose im2col
  concat lifts K to kh*kw*Cin with one pad for the whole concat) — the
  RGB stem and every cin < 64 class route to patches.
- ``custom_vjp``: dx re-enters the same kernel on the (kh-1,kw-1)-padded
  cotangent with the spatially-rotated, IO-transposed kernel; dw is kh*kw
  plain window-slice dots (weight-sized outputs — no large intermediate).
  Everything outside ``_core`` (padding, phase slices, sums) is plain
  differentiable jnp, so autodiff composes.

Numerics: pinned against ``lax.conv_general_dilated`` in
tests/test_conv_mxu.py (fwd + grads, every shape class in the model zoo).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

from .conv import _explicit_padding, conv2d_patches

Padding = Union[str, Sequence[tuple[int, int]]]

# Minimum useful-lane fraction for the Pallas route.  The kernel's
# explicit cin→128 lane padding (_core_fwd_impl) means the MXU contraction
# always runs at ceil(cin/128)*128 lanes: at cin=16 that is 8× zero-column
# MACs, at cin=64 exactly 2×.  The im2col path pays no such per-tap waste
# (its K dim is kh*kw*cin, one lane pad for the whole concat) but blows up
# HBM traffic kh*kw-fold, so the Pallas route stays the winner down to 50%
# utilization and loses below it — route on the estimated waste ratio, not
# a bare cin threshold (round-5 advisor: the old _MIN_CIN=16 floor sent
# 16 ≤ cin < 64 classes to the kernel at up to 8× wasted MACs).
_MXU_MIN_LANE_UTIL = 0.5
_LANES = 128
# VMEM budget for the manually-DMA'd input slab (bytes).  Conservative:
# the auto-pipelined kernel/output blocks and the f32 accumulator share
# the ~16 MiB VMEM with it.
_SLAB_BUDGET = 4 * 1024 * 1024
# Target rows for the GEMM M dimension per grid step.
_M_TARGET = 1024
# Whole-kernel VMEM budget for the tile search (bytes).  v5e has 16 MiB;
# leave headroom for Mosaic's own spills.  Calibrated empirically with
# the chipless r5 compile sweep: estimates ≤10.1 MiB all compile, the
# 11.4 MiB dx class (128,11,16,512)x(3,3,512,512) still OOMs — the
# budget sits between those observations.
_VMEM_BUDGET = int(10.5 * 1024 * 1024)


def _divisors_desc(n: int):
    out = [d for d in range(n, 0, -1) if n % d == 0]
    return out


def _vmem_estimate(bb, boh, bco, ow, wp, cin, kh, kw, itemsize, pipelined):
    """Upper-bound VMEM footprint of one grid step: the slab scratch
    (doubled when pipelined), the auto-pipelined kernel/output blocks
    (double-buffered by Pallas), and the stack transients the unrolled
    tap loop keeps live (the whole-slab load, one window, the f32
    accumulator plus one dot result).  Heuristic, but it separated the
    compiling from the OOMing shape classes exactly on hardware."""
    rows = boh + kh - 1
    slab = (2 if pipelined else 1) * bb * rows * wp * cin * itemsize
    kblk = 2 * kh * kw * cin * bco * itemsize
    oblk = 2 * bb * boh * ow * bco * itemsize
    m = bb * boh * ow
    transients = (
        bb * rows * wp * cin * itemsize  # xs: the slab loaded as a value
        + m * cin * itemsize             # one shifted window
        + 2 * m * bco * 4                # f32 accumulator + dot output
    )
    return slab + kblk + oblk + transients


def _pick_tiles(b, oh, ow, wp, cin, cout, kh, itemsize,
                slab_budget=_SLAB_BUDGET, kw=None, pipelined=False):
    """(bb, boh, bco): batch-fold, output-row tile, out-channel tile.

    boh: largest divisor of OH whose halo slab fits ``slab_budget`` with
    M = boh*OW not far past the target.  bb: fold batch images into the
    GEMM M dim when one image's rows leave the MXU starved (deep 7x7
    feature maps).  bco: largest divisor of Cout <= 256.  The pipelined
    kernel passes a HALVED budget: it allocates two slabs, and the 4 MiB
    default is already conservative because the auto-pipelined
    kernel/output blocks and the f32 accumulator share VMEM with it.
    """
    boh = 1
    for d in _divisors_desc(oh):
        slab = (d + kh - 1) * wp * cin * itemsize
        if slab <= slab_budget and d * ow <= 2 * _M_TARGET:
            boh = d
            break
    bb = 1
    for d in _divisors_desc(b):
        slab = d * (boh + kh - 1) * wp * cin * itemsize
        if slab <= slab_budget and d * boh * ow <= 2 * _M_TARGET:
            bb = d
            break
    # Mosaic block rule: the block's last dim must be a multiple of 128
    # or equal the full array dim.  Inception-style channel counts (384,
    # 320, 448...) have divisors ≤256 that satisfy neither, so restrict
    # the search and fall back to channel-full blocks (always legal).
    bcos = [d for d in _divisors_desc(cout)
            if d <= 256 and (d % 128 == 0 or d == cout)] or [cout]
    bco = bcos[0]
    # Whole-step VMEM check: the slab/M caps alone let the cin=512
    # classes (ResNet-50 c5) assemble a 12.6 MiB step that OOMs VMEM on
    # hardware.  Shrink in cheapness order — bco first (same total HBM
    # traffic, just more j steps over the persistent slab), then bb,
    # then boh (both cut the GEMM M) — and take the first combo that
    # fits.
    kw_eff = kw if kw is not None else kh
    for cboh in [d for d in _divisors_desc(oh) if d <= boh]:
        for cbb in [d for d in _divisors_desc(b) if d <= bb]:
            for cbco in bcos:
                if _vmem_estimate(cbb, cboh, cbco, ow, wp, cin, kh,
                                  kw_eff, itemsize,
                                  pipelined) <= _VMEM_BUDGET:
                    return cbb, cboh, cbco
    return 1, 1, bcos[-1]


def _accumulate_taps(xs, k_ref, y_ref, *, kh, kw, bb, boh, ow, cin, bco):
    """The kh*kw implicit-GEMM contraction + output write, shared by the
    synchronous and pipelined kernels (one definition so the A/B arms
    cannot diverge in the math they compare)."""
    acc = jnp.zeros((bb * boh * ow, bco), jnp.float32)
    for dy in range(kh):
        for dx in range(kw):
            win = lax.slice(
                xs, (0, dy, dx, 0), (bb, dy + boh, dx + ow, cin)
            ).reshape(bb * boh * ow, cin)
            acc += lax.dot_general(
                win, k_ref[dy, dx], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
    y_ref[...] = acc.reshape(bb, boh, ow, bco).astype(y_ref.dtype)


def _core_kernel(x_hbm, k_ref, y_ref, slab, sem, *, kh, kw, bb, boh, ow,
                 cin, bco, interpreted):
    import jax.experimental.pallas as pl

    i = pl.program_id(1)
    j = pl.program_id(2)
    rows = boh + kh - 1

    # One halo slab per (b, i); j only cycles output-channel tiles over
    # the same input rows, so on hardware copy on its first visit only
    # (Mosaic scratch persists across sequential grid steps).  The
    # interpreter reinitializes scratch per grid point, so there the copy
    # runs every step — same data, so numerics are identical.
    @pl.when(jnp.logical_or(j == 0, interpreted))
    def _copy():
        from jax.experimental.pallas import tpu as pltpu

        b0 = pl.program_id(0) * bb
        cp = pltpu.make_async_copy(
            x_hbm.at[pl.ds(b0, bb), pl.ds(i * boh, rows)], slab, sem
        )
        cp.start()
        cp.wait()

    _accumulate_taps(
        slab[...], k_ref, y_ref,
        kh=kh, kw=kw, bb=bb, boh=boh, ow=ow, cin=cin, bco=bco,
    )


def _core_kernel_pipelined(
    x_hbm, k_ref, y_ref, slab2, sem2, *, kh, kw, bb, boh, ow, cin, bco,
    n_b, n_i, interpreted,
):
    """Double-buffered variant of :func:`_core_kernel` (opt-in via
    DTM_CONV_MXU_PIPELINE): the halo-slab DMA for block N+1 is started
    right after block N's slab arrives, so the copy overlaps block N's
    n_j compute steps instead of stalling block N+1's first step.  The
    plain kernel's copy is synchronous (start+wait inline), which for
    small-Cout stages (n_j == 1, e.g. every ResNet stage-1 conv) puts a
    full slab DMA on the critical path of EVERY grid step.

    Costs/constraints: 2x slab VMEM; ALL grid dims must be "arbitrary"
    (cross-block prefetch assumes strict sequential order — fine on
    single-TensorCore v5e, surrenders Megacore splitting elsewhere).
    ``slab2``/``sem2`` carry a leading parity dim of 2; blocks alternate
    slots by linear block index.  Under the interpreter scratch does not
    persist across grid points, so interpreted mode degrades to the
    synchronous copy-every-step scheme — numerics identical, pipelining
    itself is Mosaic-only behavior (validated by the hardware canary
    before the A/B arm runs).
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bq = pl.program_id(0)
    i = pl.program_id(1)
    j = pl.program_id(2)
    rows = boh + kh - 1
    blk = bq * n_i + i  # linear (b, i) block index; j cycles inside it
    parity = jax.lax.rem(blk, 2)

    def copy_for(tblk, slot):
        tb = tblk // n_i
        ti = jax.lax.rem(tblk, n_i)
        return pltpu.make_async_copy(
            x_hbm.at[pl.ds(tb * bb, bb), pl.ds(ti * boh, rows)],
            slab2.at[slot],
            sem2.at[slot],
        )

    if interpreted:
        # Degraded interpreter scheme: synchronous copy every step into
        # this block's slot (scratch does not persist across steps).
        cp = copy_for(blk, parity)
        cp.start()
        cp.wait()
    else:
        # First block of the whole grid: nothing prefetched it.
        @pl.when(jnp.logical_and(blk == 0, j == 0))
        def _prime():
            copy_for(0, 0).start()

        @pl.when(j == 0)
        def _arrive_and_prefetch():
            copy_for(blk, parity).wait()

            @pl.when(blk + 1 < n_b * n_i)
            def _prefetch_next():
                copy_for(blk + 1, 1 - parity).start()

    _accumulate_taps(
        slab2[parity], k_ref, y_ref,
        kh=kh, kw=kw, bb=bb, boh=boh, ow=ow, cin=cin, bco=bco,
    )


def _pipeline_enabled() -> bool:
    """DTM_CONV_MXU_PIPELINE resolves at trace time (the DTM_CONV_IMPL
    contract: invalid values fail loudly naming the knob).  Default off
    — the synchronous kernel is the hardware-validated baseline; flip
    only with a banked A/B artifact (measured-defaults principle)."""
    env = os.environ.get("DTM_CONV_MXU_PIPELINE", "0")
    if env not in ("0", "1"):
        raise ValueError(
            f"DTM_CONV_MXU_PIPELINE must be '0' or '1', got {env!r}"
        )
    return env == "1"


def _core_fwd_impl(xpad, kernel, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, hp, wp, cin = xpad.shape
    kh, kw, _, cout = kernel.shape
    oh = hp - kh + 1
    ow = wp - kw + 1
    # Mosaic DMA slices must be 8-aligned along the sublane (W) dim; pad
    # W up to a multiple of 8.  The extra zero columns sit past the last
    # window (ow is computed from the true wp above) and are never read
    # into any output.
    wp8 = -(-wp // 8) * 8
    if wp8 != wp:
        xpad = jnp.pad(xpad, ((0, 0), (0, 0), (0, wp8 - wp), (0, 0)))
        wp = wp8
    # Mosaic tiles the (W, C) minor dims as (8, 128) and physically pads
    # the lane dim, for HBM and VMEM memrefs alike — so the halo DMA's
    # memref_slice is rejected whenever cin % 128 != 0, even though the
    # slice only cuts batch/row dims (first hardware canary, r5: "Slice
    # shape along dimension 3 must be aligned to tiling (128), but is
    # 64").  Pad cin explicitly: HBM traffic is unchanged (the tiled
    # buffer already stores those lanes), only the MXU contraction pays
    # zero-column MACs, and only for sub-multiple channel counts.
    cin128 = -(-cin // 128) * 128
    if cin128 != cin:
        xpad = jnp.pad(xpad, ((0, 0), (0, 0), (0, 0), (0, cin128 - cin)))
        kernel = jnp.pad(
            kernel, ((0, 0), (0, 0), (0, cin128 - cin), (0, 0))
        )
        cin = cin128
    pipelined = _pipeline_enabled()
    bb, boh, bco = _pick_tiles(
        b, oh, ow, wp, cin, cout, kh, xpad.dtype.itemsize,
        # Two slabs must fit where one did.
        slab_budget=_SLAB_BUDGET // 2 if pipelined else _SLAB_BUDGET,
        kw=kw, pipelined=pipelined,
    )
    rows = boh + kh - 1
    if pipelined:
        body = functools.partial(
            _core_kernel_pipelined, kh=kh, kw=kw, bb=bb, boh=boh, ow=ow,
            cin=cin, bco=bco, n_b=b // bb, n_i=oh // boh,
            interpreted=bool(interpret),
        )
        scratch = [
            pltpu.VMEM((2, bb, rows, wp, cin), xpad.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ]
        # Cross-block prefetch assumes strict sequential grid order: ALL
        # dims arbitrary (see _core_kernel_pipelined docstring).
        semantics = ("arbitrary", "arbitrary", "arbitrary")
    else:
        body = functools.partial(
            _core_kernel, kh=kh, kw=kw, bb=bb, boh=boh, ow=ow, cin=cin,
            bco=bco, interpreted=bool(interpret),
        )
        scratch = [
            pltpu.VMEM((bb, rows, wp, cin), xpad.dtype),
            pltpu.SemaphoreType.DMA,
        ]
        # j must be "arbitrary": the j==0 slab copy feeds later j steps
        # through persistent scratch, so the channel-tile dim can be
        # neither reordered nor split across Megacore cores.  bq/i stay
        # parallel — a core slice along them always opens at j==0.
        semantics = ("parallel", "parallel", "arbitrary")
    if interpret:
        # The generic interpreter doesn't model ANY-space refs, DMA or
        # semaphores; the TPU-flavored interpreter does.
        interpret = pltpu.InterpretParams()
    return pl.pallas_call(
        body,
        grid=(b // bb, oh // boh, cout // bco),
        in_specs=[
            # HBM, not ANY: with ANY, a small-enough x gets placed in
            # VMEM with lane-padded tiling (cin 64 -> 128), and the halo
            # DMA's memref_slice then violates Mosaic's 128-alignment
            # rule even though the slice only cuts batch/row dims (first
            # hardware canary, r5: "Slice shape along dimension 3 must
            # be aligned to tiling (128), but is 64").  The kernel's
            # whole design assumes x streams from HBM anyway.
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
            pl.BlockSpec(
                (kh, kw, cin, bco), lambda bq, i, j: (0, 0, 0, j),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (bb, boh, ow, bco), lambda bq, i, j: (bq, i, 0, j),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((b, oh, ow, cout), xpad.dtype),
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=semantics
        ),
        interpret=interpret,
    )(xpad, kernel)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _core(xpad, kernel, interpret):
    """Stride-1 VALID conv, NHWC x HWIO, via the Pallas kernel."""
    return _core_fwd_impl(xpad, kernel, interpret)


def _core_fwd(xpad, kernel, interpret):
    return _core_fwd_impl(xpad, kernel, interpret), (xpad, kernel)


def _core_bwd(interpret, res, g):
    xpad, kernel = res
    kh, kw, cin, cout = kernel.shape
    _, oh, ow, _ = g.shape
    # dw: one weight-sized dot per tap — contraction over (B, OH, OW).
    taps = []
    for dy in range(kh):
        row = []
        for dx in range(kw):
            win = lax.slice(
                xpad, (0, dy, dx, 0),
                (xpad.shape[0], dy + oh, dx + ow, cin),
            )
            row.append(
                lax.dot_general(
                    win, g, (((0, 1, 2), (0, 1, 2)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            )
        taps.append(jnp.stack(row))
    dw = jnp.stack(taps).astype(kernel.dtype)
    # dx: full correlation = the same stride-1 kernel on the
    # (kh-1, kw-1)-padded cotangent with the rotated, IO-swapped kernel.
    gp = jnp.pad(g, ((0, 0), (kh - 1, kh - 1), (kw - 1, kw - 1), (0, 0)))
    krot = kernel[::-1, ::-1].transpose(0, 1, 3, 2)
    # Re-enter _core (not the raw pallas_call) so the backward pass is
    # itself differentiable — higher-order autodiff re-uses this VJP.
    dx = _core(gp, krot, interpret)
    return dx, dw


_core.defvjp(_core_fwd, _core_bwd)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:  # pragma: no cover - backend probe failure
        return False


def _mxu_lane_utilization(cin: int) -> float:
    """Fraction of MXU lanes doing useful work after the kernel's cin→128
    pad: ``cin / (ceil(cin/128)*128)``.  1.0 at lane multiples; 0.5 at
    cin=64; 0.125 at cin=16."""
    return cin / (-(-cin // _LANES) * _LANES)


def _use_mxu_kernel(kh: int, kw: int, cin: int) -> bool:
    """Padding-aware Pallas-vs-patches routing.

    1×1 convs are a bare dot in the patches path (no im2col blow-up
    exists, nothing for the kernel to win).  Otherwise route to the
    Pallas kernel only when its post-pad lane utilization clears
    ``_MXU_MIN_LANE_UTIL`` — below that the zero-column MACs the cin→128
    pad buys exceed what the halo-slab scheme saves over im2col's
    kh·kw-fold HBM blow-up.
    """
    if kh == kw == 1:
        return False
    return _mxu_lane_utilization(cin) >= _MXU_MIN_LANE_UTIL


def conv2d_mxu(x, kernel, strides=(1, 1), padding: Padding = "SAME",
               interpret: Optional[bool] = None):
    """``lax.conv_general_dilated`` (NHWC, HWIO) semantics on the Pallas
    implicit-GEMM kernel.  ``interpret=None`` auto-selects interpret mode
    off-TPU (the kernel is Mosaic-only; CPU runs use the interpreter)."""
    if interpret is None:
        interpret = not _on_tpu()
    kh, kw, cin, cout = kernel.shape
    sh, sw = strides
    if x.shape[-1] != cin:
        raise ValueError(
            f"input channels {x.shape[-1]} != kernel input channels {cin}"
        )
    if not _use_mxu_kernel(kh, kw, cin):
        # 1x1 is already a bare dot in the patches path (no im2col
        # blow-up exists); low-utilization Cin (the cin→128 lane pad's
        # zero-column MACs) wants the im2col K-dim lift — see
        # _use_mxu_kernel.
        return conv2d_patches(x, kernel, strides, padding)
    (ph0, ph1), (pw0, pw1) = _explicit_padding(
        padding, kh, kw, sh, sw, x.shape[1], x.shape[2]
    )
    if ph0 or ph1 or pw0 or pw1:
        x = jnp.pad(x, ((0, 0), (ph0, ph1), (pw0, pw1), (0, 0)))
    b, hp, wp, _ = x.shape
    oh = (hp - kh) // sh + 1
    ow = (wp - kw) // sw + 1
    if sh == 1 and sw == 1:
        return _core(x, kernel, interpret)
    # Phase decomposition: y = sum_{p,q} core(x[p::s], k[p::s]) — each
    # phase is an exact stride-1 conv on a decimated image; taps
    # partition over phases so total MACs equal the strided conv's.
    y = None
    for p in range(min(sh, kh)):
        khp = len(range(p, kh, sh))
        for q in range(min(sw, kw)):
            kwq = len(range(q, kw, sw))
            xs = lax.slice(
                x,
                (0, p, q, 0),
                (b, p + (oh + khp - 2) * sh + 1, q + (ow + kwq - 2) * sw + 1,
                 cin),
                (1, sh, sw, 1),
            )
            kp = kernel[p::sh, q::sw]
            yp = _core(xs, kp, interpret)
            y = yp if y is None else y + yp
    return y
