"""Dataset-cursor re-split for elastic fleet resize (N -> M processes).

A checkpoint written by an N-process fleet carries N per-process dataset
sidecars (``checkpoints/dataset_states/<step>/p<pid>.json``).  When the
fleet comes back with M != N processes, no process can simply adopt "its
own" sidecar: the sidecar set describes a different sharding of the input
stream.  This module holds the pure, jax-free math that maps the N saved
cursor positions onto the new fleet.

Conservative rule (never skip an untrained batch): every new process
resumes from the *fleet-minimum* safe position across the N saved
cursors.  Sidecars are written at the same checkpoint step, so their
positions differ by at most the pipeline's in-flight depth — one chunk —
and adopting the minimum re-reads at most that much per host.  Re-reading
a batch costs a few redundant gradients; skipping one silently biases the
run, so the trade is always taken in the re-read direction.

Cursor formats (``data/datasets.py``), ranked by a total-order position
key so "minimum" is well defined:

- ``{"epoch", "batch_idx"}``  (ArrayDataset)   -> (epoch, batch_idx)
- ``{"epoch", "pos"}``        (PTBDataset)     -> (epoch, pos)
- ``{"records", "count"}``    (TFRecord shard) -> (0, count)

The first two are *global* cursors — every process materialises its own
row block of the same global batch — so the N saved positions agree and
the minimum is exact: an N->M resume replays the identical global batch
sequence.  The TFRecord ``count`` cursor is per-shard in file-sharded
mode; the minimum there is genuinely conservative (bounded re-read).

Nothing here talks to the network.  The *decision* (which saved pid's
cursor to adopt) is deterministic given the sidecar set, but hosts may
race sidecar reads, so callers must still funnel the pick through
``resilience/consensus.py`` (chief broadcasts, followers adopt) before
acting on it — see ``harness/checkpoint.py``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

# Sentinel returned by pick_source when no sidecar exposes a usable
# position; callers fall back to the primary's orbax-saved state.
NO_SOURCE = -1


def cursor_position(state: Any) -> Optional[Tuple[int, int]]:
    """Total-order position key for one saved dataset state, or None.

    Accepts either a raw dataset cursor dict or the sidecar payload shape
    ``{"dataset": cursor}`` written by the train harness.  Unknown
    formats return None and are ignored by the re-split (conservative:
    an unreadable position can never be chosen as the resume point).
    """
    if not isinstance(state, dict):
        return None
    if "dataset" in state and isinstance(state["dataset"], dict):
        return cursor_position(state["dataset"])
    try:
        if "batch_idx" in state:
            return (int(state["epoch"]), int(state["batch_idx"]))
        if "pos" in state:
            return (int(state["epoch"]), int(state["pos"]))
        if "count" in state:
            return (0, int(state["count"]))
    except (KeyError, TypeError, ValueError):
        return None
    return None


def pick_source(states: Dict[int, Any]) -> int:
    """Choose the saved pid whose cursor is the fleet-minimum position.

    Deterministic: ties break toward the lowest pid, so every host that
    reads the same sidecar set computes the same answer.  Returns
    NO_SOURCE (-1) when no state exposes a parseable position.
    """
    best = NO_SOURCE
    best_key: Optional[Tuple[int, int, int]] = None
    for pid in sorted(states):
        pos = cursor_position(states[pid])
        if pos is None:
            continue
        key = (pos[0], pos[1], pid)
        if best_key is None or key < best_key:
            best, best_key = pid, key
    return best


def resplit_states(
    states: Dict[int, Any], new_nproc: int
) -> Tuple[int, Dict[int, Any]]:
    """Map N saved cursor states onto an M-process fleet.

    Returns ``(source_pid, {new_pid: state})``: every new process adopts
    the fleet-minimum source cursor (global-cursor datasets make this
    exact; per-shard cursors re-read at most one chunk).  1 -> 1 is the
    identity: the single saved state is handed back unmodified, so a
    same-shape resume stays bit-identical to a non-resized one.

    Raises ValueError when no saved state has a usable position — the
    caller decides the fallback (primary's approximate position).
    """
    src = pick_source(states)
    if src == NO_SOURCE:
        raise ValueError("no saved dataset state exposes a usable cursor position")
    return src, {pid: states[src] for pid in range(new_nproc)}


def describe_positions(states: Dict[int, Any]) -> Dict[str, Any]:
    """Ledger-friendly summary: per-pid position keys plus the pick."""
    positions = {
        str(pid): (
            list(pos)
            if (pos := cursor_position(states[pid])) is not None
            else None
        )
        for pid in sorted(states)
    }
    return {"positions": positions, "source_pid": pick_source(states)}
