"""Multi-process launcher — the L6 layer, TPU-native form.

The reference's outermost layer is per-model shell scripts that spawn N
``ps`` + M ``worker`` Python processes across hosts, passing ``--job_name``
and ``--task_index`` flags that each driver turns into a ``ClusterSpec`` +
``tf.train.Server`` (SURVEY.md §1 L6, §2.1 R1; TF training/server_lib.py:
96,107-146,242).  There is no resource manager — placement is manual.

The SPMD equivalent is radically smaller: every process runs the *same*
program; the only per-process facts are ``(coordinator_address,
num_processes, process_id)``, wired into ``jax.distributed.initialize``
(control plane only — the data plane is compiled XLA collectives over
ICI/DCN, SURVEY.md §5.8).  This module provides:

- the ``DTM_*`` environment convention carrying those three facts
  (the analogue of R1's ``--job_name/--task_index`` flags),
- :func:`initialize_from_env` — process-side bootstrap,
- :func:`launch_local` — spawn an N-process cluster on localhost
  (the analogue of TF's in-process fake clusters via
  ``Server.create_local_server``, SURVEY.md §4: multi-node protocol tests
  on one machine with no real cluster), now a *supervisor*: children
  heartbeat (``resilience/heartbeat.py``) and a dead or stalled child
  tears the fleet down in seconds (SIGTERM → grace → SIGKILL) instead
  of leaving survivors hung in collectives,
- :func:`supervise_local` — the fleet restart loop (relaunch +
  checkpoint auto-resume, deterministic-jitter backoff),
- :class:`FleetAutoscaler` — the closed-loop serving scale controller
  (``launch_local(scale_controller=...)``): tails the replicas' own
  telemetry artifacts, feeds a pure hysteresis policy, and recruits or
  drains replicas mid-stream with the exactly-once file-queue
  protocol guaranteeing no response is dropped or duplicated,
- a CLI: ``python -m distributed_tensorflow_models_tpu.launch``.

On managed TPU slices none of this is needed — ``jax.distributed
.initialize()`` auto-detects the slice topology and each host runs the same
command; use the CLI only for manual clusters and localhost tests.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import subprocess
import sys
from typing import Mapping, Optional, Sequence

log = logging.getLogger("dtm")

ENV_COORDINATOR = "DTM_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "DTM_NUM_PROCESSES"
ENV_PROCESS_ID = "DTM_PROCESS_ID"
ENV_CPU_DEVICES = "DTM_CPU_DEVICES_PER_PROCESS"

DEFAULT_PORT = 9671

# How long a SIGTERM'd fleet gets to drain (emergency checkpoints) before
# the supervisor SIGKILLs the stragglers.  A host hung in a dead peer's
# collective never reaches its chunk-boundary preemption poll — the KILL
# is what actually ends it; a healthy host exits resumable well inside
# the default.
DEFAULT_TERM_GRACE_S = 15.0
_MONITOR_POLL_S = 0.2

# Exit code a preempted-but-checkpointed training process uses (BSD
# EX_TEMPFAIL): the run wrote an emergency checkpoint on SIGTERM and
# rerunning the same command resumes it.  ``launch_local`` reports such
# children as resumable instead of replaying their logs as a failure,
# and propagates the code so outer supervisors can requeue.
RESUMABLE_EXIT_CODE = 75


def aggregate_exit_codes(codes) -> int:
    """Cluster exit code: a real failure always wins over "preempted"
    (one resumable child must not relabel another child's crash as
    resumable), preempted wins over success, all-zero is success."""
    failures = [c for c in codes if c not in (0, RESUMABLE_EXIT_CODE)]
    if failures:
        return max(failures)
    if RESUMABLE_EXIT_CODE in codes:
        return RESUMABLE_EXIT_CODE
    return 0


def initialize_from_env() -> bool:
    """Bootstrap ``jax.distributed`` from ``DTM_*`` env vars.

    Returns True if a multi-process cluster was configured, False when the
    env carries no cluster facts (single-process mode — the common case, and
    the analogue of running a reference driver without ``--job_name``).

    Must run before first backend use.  When ``DTM_CPU_DEVICES_PER_PROCESS``
    is set the process is forced onto that many fake CPU devices first
    (test clusters, SURVEY.md §4.3) and gloo cross-process collectives are
    enabled so psum/all-gather actually cross process boundaries.
    """
    cpu_devices = os.environ.get(ENV_CPU_DEVICES)
    if cpu_devices:
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={cpu_devices}"
        if "xla_force_host_platform_device_count" in flags:
            # Replace an inherited count (e.g. the test conftest's 8).
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", want, flags
            )
        else:
            flags = f"{flags} {want}".strip()
        os.environ["XLA_FLAGS"] = flags
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")

    coord = os.environ.get(ENV_COORDINATOR)
    nproc = os.environ.get(ENV_NUM_PROCESSES)
    pid = os.environ.get(ENV_PROCESS_ID)

    # Fleet heartbeat (DTM_HEARTBEAT_DIR, set by the supervising
    # launcher): started HERE — before the heavy jax/backend imports
    # below — so the supervisor sees a first beat within ~a second of
    # spawn and a child that dies during initialization is still
    # attributable.  No-op when the env var is absent.
    from distributed_tensorflow_models_tpu.resilience import heartbeat

    heartbeat.start_from_env(int(pid) if pid else 0)

    if not (coord and nproc and pid):
        return False

    from distributed_tensorflow_models_tpu.core.mesh import (
        initialize_multihost,
    )

    initialize_multihost(
        coordinator_address=coord,
        num_processes=int(nproc),
        process_id=int(pid),
    )
    return True


def _terminate_fleet(
    procs: Sequence[subprocess.Popen],
    codes: dict[int, int],
    grace_s: float,
) -> None:
    """SIGTERM every still-running child (→ their preemption-grace
    emergency checkpoints, where reachable), wait up to ``grace_s``,
    SIGKILL the stragglers (a host hung in a dead peer's collective
    never reaches its chunk-boundary poll).  Fills ``codes``."""
    import time

    for i, p in enumerate(procs):
        if i not in codes and p.poll() is None:
            try:
                p.terminate()
            except OSError:  # already reaped
                pass
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        if all(
            i in codes or p.poll() is not None for i, p in enumerate(procs)
        ):
            break
        time.sleep(_MONITOR_POLL_S)
    for i, p in enumerate(procs):
        if i in codes:
            continue
        if p.poll() is None:
            sys.stderr.write(
                f"--- fleet: process {i} did not exit within the "
                f"{grace_s:.0f}s grace period; killing it ---\n"
            )
            p.kill()
            p.wait()
        codes[i] = p.returncode


class FleetAutoscaler:
    """Closed-loop scale controller for ``launch_local`` serving fleets.

    The serving replicas publish their load as artifacts (that is the
    whole observability design): ``timeseries_p<i>.jsonl`` rows carry
    each replica's cumulative ``offered``/``served`` counters plus the
    instantaneous gauges (``serve/blocks_free``, ``serve/slo_margin/*``),
    and the shared file queue holds whatever no replica has claimed
    yet.  This controller tails both from the *supervisor* process —
    no RPC into the replicas — folds them into one backlog figure::

        backlog = unclaimed queue files
                + Σ offered_i − Σ served_i     (claimed but unfinished)

    and feeds it to an :class:`~.serving.admission.AutoscalePolicy`
    (pure hysteresis: consecutive-observation streaks + cooldown, so a
    single spike cannot flap the fleet).  ``launch_local`` invokes
    :meth:`decide` from its monitor loop and performs the mechanics
    (spawn / SIGTERM-drain); the policy object only ever says +1/-1/0.

    Every decision leaves a full forensic trail in ``workdir``:

    - ``scale_events.jsonl`` — one line per decision with the
      triggering signal values (``serving_report.py`` renders the
      timeline against throughput),
    - ``flight_autoscale_<k>.json`` — a flight-recorder dump whose
      ring holds every evaluation instant leading up to decision k,
    - ``fleet_size.json`` (atomic rename) — the commitment replicas
      started with ``--fleet-file`` mirror into their own registries
      (``serve/fleet_size`` + ``serve/scale_up|down``).

    jax-free and wall-clock-stamping by design: like
    ``telemetry/timeseries.py`` this file is deliberately OUTSIDE
    dtm-lint's determinism scope — event logs need wall time; the
    *decisions* come from the pure policy, which is inside it.
    """

    def __init__(
        self,
        workdir: str,
        *,
        policy=None,
        queue_dir: Optional[str] = None,
        poll_interval_s: float = 0.5,
        fleet_file: Optional[str] = None,
        ring_events: int = 512,
    ):
        from distributed_tensorflow_models_tpu.serving import (
            admission as admlib,
        )
        from distributed_tensorflow_models_tpu.telemetry import (
            registry as reglib,
        )
        from distributed_tensorflow_models_tpu.telemetry import (
            trace as tracelib,
        )

        self.workdir = workdir
        self.queue_dir = queue_dir
        self.policy = (
            policy if policy is not None else admlib.AutoscalePolicy()
        )
        self.poll_interval_s = float(poll_interval_s)
        self.fleet_file = fleet_file or os.path.join(
            workdir, "fleet_size.json"
        )
        self.events_path = os.path.join(workdir, "scale_events.jsonl")
        self.events = 0
        self._last_poll = float("-inf")
        self._size_written: Optional[int] = None
        # Controller-side registry + tracer: the flight record dumped at
        # each decision carries the evaluation instants that led to it.
        self._registry = reglib.MetricsRegistry()
        self._registry.trace = tracelib.Tracer(ring_events)

    # -- signal collection -------------------------------------------------

    @staticmethod
    def _tail_row(path: str) -> Optional[dict]:
        """Last parseable row of one replica's timeseries file."""
        try:
            with open(path, "rb") as f:
                lines = f.read().splitlines()
        except OSError:
            return None
        for raw in reversed(lines):
            try:
                row = json.loads(raw)
            except ValueError:
                continue  # torn tail line: take the previous row
            if isinstance(row, dict):
                return row
        return None

    def signals(self, live: Sequence[int]) -> dict:
        """Fold the fleet's artifacts into the autoscale inputs."""
        offered = served = 0.0
        blocks_free = None
        margins: dict = {}
        per_replica: dict = {}
        for i in live:
            row = self._tail_row(
                os.path.join(self.workdir, f"timeseries_p{i}.jsonl")
            )
            if row is None:
                continue
            offered += float(row.get("offered", 0.0))
            served += float(row.get("served", 0.0))
            bf = row.get("serve/blocks_free")
            if bf is not None:
                blocks_free = (
                    bf if blocks_free is None else min(blocks_free, bf)
                )
            for key, val in row.items():
                if key.startswith("serve/slo_margin/"):
                    name = key.rsplit("/", 1)[-1]
                    margins[name] = min(
                        margins.get(name, float("inf")), float(val)
                    )
            per_replica[i] = {
                "offered": row.get("offered", 0.0),
                "served": row.get("served", 0.0),
                "blocks_free": bf,
            }
        unclaimed = 0
        if self.queue_dir is not None:
            try:
                unclaimed = sum(
                    1
                    for name in os.listdir(self.queue_dir)
                    if name.startswith("req-") and name.endswith(".json")
                )
            except OSError:
                unclaimed = 0
        return {
            "backlog": unclaimed + max(0.0, offered - served),
            "unclaimed": unclaimed,
            "offered": offered,
            "served": served,
            "blocks_free": blocks_free,
            "slo_margins": margins,
            "slo_breached": sorted(
                n for n, m in margins.items() if m < 0.0
            ),
            "per_replica": per_replica,
        }

    # -- commitment --------------------------------------------------------

    def _write_fleet_file(self, size: int) -> None:
        import time

        if size == self._size_written:
            return
        tmp = f"{self.fleet_file}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"size": int(size), "ts_wall": time.time()}, f)
        os.replace(tmp, self.fleet_file)
        self._size_written = size

    def decide(self, live: Sequence[int]) -> int:
        """One monitor-loop tick: returns +1 (recruit a replica), -1
        (drain one), or 0.  Rate-limited to ``poll_interval_s``; the
        caller owns the process mechanics and victim choice."""
        import time

        now = time.perf_counter()
        if self._size_written is None:
            # Initial commitment only: afterwards the fleet file tracks
            # DECISIONS, never observed liveness — a draining victim is
            # still live for a few ticks, and mirroring that back would
            # flap the file (and the replicas' scale counters) without
            # any scale event having happened.
            self._write_fleet_file(len(live))
        if now - self._last_poll < self.poll_interval_s or not live:
            return 0
        self._last_poll = now
        sig = self.signals(live)
        delta = self.policy.observe(
            replicas=len(live),
            backlog=sig["backlog"],
            slo_breached=bool(sig["slo_breached"]),
        )
        self._registry.trace.instant(
            "autoscale/evaluate",
            {
                "replicas": len(live),
                "backlog": sig["backlog"],
                "unclaimed": sig["unclaimed"],
                "blocks_free": sig["blocks_free"],
                "slo_breached": sig["slo_breached"],
                "delta": delta,
            },
        )
        if delta == 0:
            return 0
        event = "scale_up" if delta > 0 else "scale_down"
        record = {
            "ts_wall": time.time(),
            "event": event,
            "from_size": len(live),
            "to_size": len(live) + delta,
            "live": sorted(int(i) for i in live),
            **{k: v for k, v in sig.items() if k != "per_replica"},
            "per_replica": sig["per_replica"],
        }
        with open(self.events_path, "a") as f:
            f.write(json.dumps(record) + "\n")
        self._registry.trace.instant(f"autoscale/{event}", dict(record))
        self._registry.trace.dump_flight_record(
            os.path.join(
                self.workdir, f"flight_autoscale_{self.events}.json"
            ),
            f"autoscale_{event}",
            registry=self._registry,
        )
        self.events += 1
        self._write_fleet_file(len(live) + delta)
        sys.stderr.write(
            f"--- fleet: autoscale {event} {len(live)} -> "
            f"{len(live) + delta} (backlog {sig['backlog']:.0f}, "
            f"unclaimed {sig['unclaimed']}, slo_breached "
            f"{sig['slo_breached']}) ---\n"
        )
        return delta


def launch_local(
    num_processes: int,
    argv: Sequence[str],
    *,
    port: int = DEFAULT_PORT,
    cpu_devices_per_process: int | None = None,
    extra_env: Mapping[str, str] | None = None,
    timeout: float | None = None,
    heartbeat_timeout: float | None = None,
    term_grace_s: float = DEFAULT_TERM_GRACE_S,
    startup_stats: Optional[dict] = None,
    scale_controller: Optional[FleetAutoscaler] = None,
) -> list[int]:
    """Spawn ``num_processes`` copies of ``argv`` as a localhost cluster.

    Each child gets the ``DTM_*`` cluster facts in its environment; process
    0's stdout/stderr pass through, the rest stream into temp files and are
    replayed only on failure (mirroring the reference launch scripts'
    per-task logs, R1).  Files, not pipes: a sequentially-drained pipe
    back-pressures a chatty child into blocking mid-step, which stalls the
    whole cluster at its next collective.  ``timeout`` bounds the *total*
    wall time of the cluster, not each child.  Returns the exit codes.

    **Supervision.**  The launcher polls the fleet instead of waiting on
    children in order: the moment any child dies with a real failure
    (exit not 0/75 — e.g. a ``kill -9``), the survivors are SIGTERM'd
    promptly and SIGKILL'd after ``term_grace_s`` — seconds of teardown
    instead of every peer hanging to its collective timeout.  Each child
    also gets a heartbeat directory (``DTM_HEARTBEAT_DIR``;
    ``resilience/heartbeat.py`` — written by ``initialize_from_env``,
    stepped by ``fit``, and read back by the chief's ``fleet/*``
    gauges); with ``heartbeat_timeout`` set, a child whose heartbeat
    goes stale that long (wedged, not dead) triggers the same fleet
    teardown, attributed to its process index.  Only pass
    ``heartbeat_timeout`` for commands that actually heartbeat — i.e.
    anything calling ``initialize_from_env`` — and size it over the
    slowest expected gap (initial jax import + first XLA compile beat
    the interval automatically; the writer thread starts pre-import).

    **Startup MTTR.**  Pass ``startup_stats`` (a dict, filled in place
    per process index) to stamp the relaunch-to-first-step milestones
    off the heartbeat files: ``first_beat_s`` (spawn → first heartbeat,
    i.e. process up), ``loop_entry_s`` (spawn → step ≥ 0, i.e. restore +
    setup done, entering the train loop) and ``first_step_s`` (spawn →
    first observed step *advance* past the entry step).  Readings are at
    heartbeat-interval resolution — ``supervise_local`` prints them per
    relaunch, and the precise in-process numbers live in the workdir's
    ``telemetry.json`` ``startup`` section.  ``first_step_s`` may be
    absent when chunks outrun the heartbeat cadence (the first observed
    beat already carries an advanced step).

    **Closed-loop autoscale** (serving fleets).  Pass a
    :class:`FleetAutoscaler` as ``scale_controller`` and the monitor
    polls it each round: +1 spawns one more child at a FRESH process
    index (same command/env recipe — file-queue replicas join the
    shared queue and start claiming immediately), -1 SIGTERMs the
    highest-index live child, whose drain path answers everything it
    already claimed and exits 0 — the monitor treats that like any
    benign exit, the fleet keeps running, and the exactly-once queue
    protocol guarantees no response is dropped or duplicated across
    the membership change.  The returned code list covers every child
    ever spawned, not just the initial ``num_processes``.
    """
    import shutil
    import tempfile
    import time

    from distributed_tensorflow_models_tpu.resilience import heartbeat

    procs: list[subprocess.Popen] = []
    logs: list = []
    hb_dir = tempfile.mkdtemp(prefix="dtm-heartbeat-")
    t0_wall = time.time()

    def _spawn(i: int) -> None:
        """Spawn child i (initial fleet member or autoscale recruit —
        a recruit gets a fresh, never-reused process index so its
        artifacts and queue claims can't collide with history)."""
        env = dict(os.environ)
        env[ENV_COORDINATOR] = f"127.0.0.1:{port}"
        env[ENV_NUM_PROCESSES] = str(max(num_processes, i + 1))
        env[ENV_PROCESS_ID] = str(i)
        env[heartbeat.ENV_HEARTBEAT_DIR] = hb_dir
        if cpu_devices_per_process is not None:
            env[ENV_CPU_DEVICES] = str(cpu_devices_per_process)
        if extra_env:
            env.update(extra_env)
        log = None
        if i != 0:
            log = tempfile.TemporaryFile(
                mode="w+", prefix=f"dtm-launch-{i}-"
            )
        logs.append(log)
        procs.append(
            subprocess.Popen(
                list(argv),
                env=env,
                stdout=None if i == 0 else log,
                stderr=None if i == 0 else subprocess.STDOUT,
            )
        )

    try:
        for i in range(num_processes):
            _spawn(i)
        def _stamp_startup() -> None:
            """Relaunch-to-first-step milestones from the heartbeat
            files (see the docstring); called once per poll round.
            Times come from each beat's own write timestamp (payload
            ``time``), not this reader's clock — a milestone whose beat
            is only *observed* by a later poll (or the final read after
            the fleet exits) is still stamped at the moment it was
            written, bounded by the writer's ~1 s cadence."""
            for i, view in enumerate(
                heartbeat.read_fleet(hb_dir, len(procs))
            ):
                if view is None:
                    continue
                at = round(float(view.get("time", 0.0)) - t0_wall, 3)
                st = startup_stats.setdefault(i, {})
                st.setdefault("first_beat_s", at)
                step = int(view.get("step", -1))
                if step >= 0 and "loop_entry_s" not in st:
                    st["loop_entry_s"] = at
                    st["_entry_step"] = step
                if (
                    "loop_entry_s" in st
                    and "first_step_s" not in st
                    and step > st["_entry_step"]
                ):
                    st["first_step_s"] = at

        deadline = None if timeout is None else time.monotonic() + timeout
        codes: dict[int, int] = {}
        failure: Optional[tuple[int, str]] = None
        while len(codes) < len(procs):
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired(argv, timeout)
            if startup_stats is not None:
                _stamp_startup()
            for i, p in enumerate(procs):
                if i in codes:
                    continue
                rc = p.poll()
                if rc is None:
                    continue
                codes[i] = rc
                if rc not in (0, RESUMABLE_EXIT_CODE) and failure is None:
                    try:
                        why = f"died on {signal.Signals(-rc).name}"
                    except ValueError:
                        why = f"exited {rc}"
                    failure = (i, why)
            if failure is not None:
                break
            if heartbeat_timeout is not None and len(codes) < len(procs):
                views = heartbeat.read_fleet(hb_dir, len(procs))
                for i, p in enumerate(procs):
                    if i in codes:
                        continue
                    view = views[i]
                    age = (
                        view["age_s"]
                        if view is not None
                        else time.time() - t0_wall
                    )
                    if age > heartbeat_timeout:
                        # Step + phase from the heartbeat payload: the
                        # stall is attributed ("frozen at step 40 in
                        # phase save") without traces — the flight
                        # recorder / fleet_report.py pick up from here.
                        failure = (
                            i,
                            f"heartbeat stale for {age:.1f}s "
                            f"(> {heartbeat_timeout:.1f}s; last step "
                            f"{'?' if view is None else view.get('step')}, "
                            "phase "
                            f"{'?' if view is None else view.get('phase', '?')})",
                        )
                        break
            if failure is not None:
                break
            if scale_controller is not None:
                live = [
                    i for i, p in enumerate(procs)
                    if i not in codes and p.poll() is None
                ]
                delta = scale_controller.decide(live)
                if delta > 0:
                    # Recruit: fresh max index, same command — the new
                    # replica joins the shared queue mid-stream.
                    _spawn(len(procs))
                elif delta < 0 and len(live) > 1:
                    # Drain the newest live replica: SIGTERM stops its
                    # claiming, it answers what it owns, exits 0.
                    victim = max(live)
                    sys.stderr.write(
                        f"--- fleet: autoscale draining process "
                        f"{victim} (SIGTERM; it answers its claimed "
                        "work, then exits) ---\n"
                    )
                    try:
                        procs[victim].terminate()
                    except OSError:  # exited between poll and signal
                        pass
            time.sleep(_MONITOR_POLL_S)
        if failure is not None:
            i, why = failure
            sys.stderr.write(
                f"--- fleet: process {i} {why}; terminating the rest of "
                "the fleet (survivors take the emergency-checkpoint "
                "grace path where reachable) ---\n"
            )
            # A stalled (still-running) culprit gets the same
            # SIGTERM-then-SIGKILL as its peers.
            _terminate_fleet(procs, codes, term_grace_s)
        if startup_stats is not None:
            # One last read: the final beats (written right up to child
            # exit) may carry the first step advance the poll missed.
            _stamp_startup()
            for st in startup_stats.values():
                st.pop("_entry_step", None)
        code_list = [codes[i] for i in range(len(procs))]
        for i, rc in enumerate(code_list):
            if rc == RESUMABLE_EXIT_CODE:
                # Preemption grace, not a failure: the child checkpointed
                # and asked to be rerun — don't dump its log as a crash.
                sys.stderr.write(
                    f"--- process {i} preempted (exit {rc}): "
                    "resumable — rerun the same command ---\n"
                )
            elif rc != 0 and i != 0:
                logs[i].seek(0)
                sys.stderr.write(
                    f"--- process {i} (exit {rc}) ---\n"
                    f"{logs[i].read()}\n"
                )
        return code_list
    except BaseException:
        for p in procs:
            if p.poll() is None:
                p.kill()
        raise
    finally:
        for log in logs:
            if log is not None:
                log.close()
        shutil.rmtree(hb_dir, ignore_errors=True)


def supervise_local(
    num_processes: int,
    argv: Sequence[str],
    *,
    max_restarts: int = 2,
    backoff_base_s: float = 1.0,
    backoff_max_s: float = 60.0,
    seed: int = 0,
    port: int = DEFAULT_PORT,
    resize_to: int | None = None,
    auto_resize: bool = False,
    follow_checkpoints: str | None = None,
    **launch_kwargs,
) -> int:
    """``launch_local`` under the fleet restart loop: a fleet torn down
    for a real failure (one host killed/stalled) is relaunched — same
    command, so every child auto-resumes from the latest checkpoint —
    up to ``max_restarts`` times, spaced by the deterministic-jitter
    backoff ``recoverable_fit`` uses for in-process restarts
    (``resilience/backoff.py``).  Per-host failure attribution goes to
    stderr each round.  Returns the final aggregate exit code; an
    all-preempted fleet (aggregate 75) returns immediately — the fleet
    was *told* to die, and the rerun belongs to whoever told it.

    Each relaunch bumps the coordinator port by one: the dead chief's
    listener can linger in TIME_WAIT, and a bind failure would burn a
    whole restart on launcher misfortune.

    Every round stamps the fleet's startup MTTR (spawn → loop entry →
    first step, from the heartbeat files — ``launch_local``'s
    ``startup_stats``) to stderr, so a relaunch's recovery time is
    visible at the supervisor without opening the workdir; the precise
    per-process numbers are the ``startup`` section of each run's
    ``telemetry.json``.

    Elastic resize: ``resize_to=M`` relaunches every restart at M
    processes instead of N — the children's cross-topology restore
    (``harness/checkpoint.py``) reshards the arrays onto the new mesh
    and re-splits the dataset cursor, so a fleet that lost (or gained)
    capacity keeps training instead of crash-looping at a process count
    it can no longer field.  ``auto_resize=True`` shrinks the fleet by
    the number of distinct failed processes on each relaunch (floor 1)
    — the "capacity is not coming back" mode for preemptible hosts.
    Both compose with the persistent XLA compile cache / AOT startup
    path: the surviving hosts' caches hold the per-shard programs, so a
    resized relaunch pays a reshard, not a cold compile, when the new
    shapes were seen before.  The children must still satisfy the batch
    contract (global batch divisible by the new process and device
    counts) — pick M accordingly.

    These two resize paths are *reactive* (a failure already happened).
    For serving fleets there is a third, *proactive* path: pass a
    :class:`FleetAutoscaler` through ``launch_kwargs`` as
    ``scale_controller`` and each launch scales WITHIN the run from
    scheduler telemetry — no failure, no relaunch, no dropped work.
    The controller object is reused across relaunches, so its
    hysteresis state and scale-event numbering survive a restart.

    Continuous deployment (ISSUE 20): ``follow_checkpoints=<dir>``
    appends ``--follow-checkpoints <dir>`` to every child's argv —
    serving replicas (including ones the autoscaler recruits
    mid-run, which clone the same argv) then follow the trainer's
    checkpoint directory, gating/canarying/promoting new weights
    live instead of waiting for a relaunch to pick them up.  The
    flag rides the argv so a fleet restart keeps following too.
    """
    import time

    from distributed_tensorflow_models_tpu.resilience import backoff

    if resize_to is not None and resize_to < 1:
        raise ValueError(f"resize_to must be >= 1, got {resize_to}")
    if follow_checkpoints:
        argv = list(argv) + ["--follow-checkpoints", follow_checkpoints]
    attempt = 0
    cur_procs = num_processes
    while True:
        stats: dict = {}
        codes = launch_local(
            cur_procs, argv, port=port + attempt,
            startup_stats=stats, **launch_kwargs
        )
        if stats:
            worst = max(
                (
                    st.get("first_step_s") or st.get("loop_entry_s") or 0.0
                    for st in stats.values()
                ),
                default=0.0,
            )
            sys.stderr.write(
                f"--- fleet startup MTTR ("
                f"{'relaunch' if attempt else 'launch'} {attempt}): "
                f"slowest spawn→first-step {worst:.1f}s; per process "
                + " ".join(
                    f"p{i}={stats[i]}" for i in sorted(stats)
                )
                + " ---\n"
            )
        agg = aggregate_exit_codes(codes)
        if agg in (0, RESUMABLE_EXIT_CODE):
            return agg
        failed = {
            i: c
            for i, c in enumerate(codes)
            if c not in (0, RESUMABLE_EXIT_CODE)
        }
        attempt += 1
        if attempt > max_restarts:
            sys.stderr.write(
                f"--- fleet: giving up after {max_restarts} restart(s); "
                f"failed processes {failed} ---\n"
            )
            return agg
        delay = backoff.restart_backoff(
            attempt, base_s=backoff_base_s, max_s=backoff_max_s, seed=seed
        )
        next_procs = cur_procs
        if resize_to is not None:
            next_procs = resize_to
        elif auto_resize:
            # Treat each distinct failed process as capacity that is not
            # coming back; the resized fleet resumes cross-topology.
            next_procs = max(1, cur_procs - len(failed))
        if next_procs != cur_procs:
            sys.stderr.write(
                f"--- fleet: RESIZING {cur_procs} -> {next_procs} "
                "process(es) on relaunch; children resume across the "
                "topology change (arrays resharded, dataset cursor "
                "re-split to the fleet-minimum position) ---\n"
            )
            cur_procs = next_procs
        sys.stderr.write(
            f"--- fleet: process(es) {sorted(failed)} failed "
            f"(exit codes {failed}); relaunching the whole fleet in "
            f"{delay:.2f}s (restart {attempt}/{max_restarts}, "
            f"coordinator port {port + attempt}, {cur_procs} "
            "process(es)) ---\n"
        )
        time.sleep(delay)


def main(argv: Sequence[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distributed_tensorflow_models_tpu.launch",
        description=(
            "Launch a command as an N-process jax.distributed cluster. "
            "Localhost mode spawns all processes; multi-host mode "
            "(--process-id given) configures this process only — run the "
            "same command on every host with its own --process-id, like "
            "the reference's per-host launch scripts."
        ),
    )
    parser.add_argument("--num-processes", type=int, required=True)
    parser.add_argument(
        "--coordinator",
        default=f"127.0.0.1:{DEFAULT_PORT}",
        help="host:port of process 0's coordination service",
    )
    parser.add_argument(
        "--process-id",
        type=int,
        default=None,
        help="multi-host mode: this host's process index; omit for "
        "localhost mode (spawns all processes here)",
    )
    parser.add_argument(
        "--cpu-devices-per-process",
        type=int,
        default=None,
        help="force N fake CPU devices per process (test clusters)",
    )
    parser.add_argument(
        "--max-restarts",
        type=int,
        default=0,
        help="localhost mode: relaunch the whole fleet (auto-resuming "
        "from checkpoints) up to N times after a real failure — the "
        "fleet-level recoverable_fit (0 = launch once)",
    )
    parser.add_argument(
        "--resize-to",
        type=int,
        default=None,
        help="localhost mode, with --max-restarts: relaunch at this "
        "process count after a failure (elastic resize; children "
        "resume across the topology change from the latest checkpoint)",
    )
    parser.add_argument(
        "--auto-resize",
        action="store_true",
        help="localhost mode, with --max-restarts: shrink the fleet by "
        "the number of failed processes on each relaunch (floor 1) — "
        "assume lost capacity is not coming back",
    )
    parser.add_argument(
        "--autoscale-workdir",
        default=None,
        help="localhost mode: enable the closed-loop serving "
        "autoscaler — tail timeseries_p<i>.jsonl under this workdir "
        "for backlog/SLO signals and scale the fleet within the run "
        "(writes scale_events.jsonl, flight_autoscale_<k>.json and "
        "fleet_size.json there)",
    )
    parser.add_argument(
        "--autoscale-queue-dir",
        default=None,
        help="with --autoscale-workdir: also count unclaimed req-*.json "
        "files in this file-queue directory as backlog",
    )
    parser.add_argument(
        "--autoscale-min", type=int, default=1,
        help="autoscaler floor on live replicas (default 1)",
    )
    parser.add_argument(
        "--autoscale-max", type=int, default=4,
        help="autoscaler ceiling on live replicas (default 4)",
    )
    parser.add_argument(
        "--autoscale-up-backlog", type=float, default=4.0,
        help="scale up when backlog per replica exceeds this",
    )
    parser.add_argument(
        "--autoscale-down-backlog", type=float, default=1.0,
        help="scale down when backlog per replica stays under this",
    )
    parser.add_argument(
        "--autoscale-interval", type=float, default=0.5,
        help="seconds between autoscaler evaluations",
    )
    parser.add_argument(
        "--follow-checkpoints",
        default=None,
        help="localhost mode: append '--follow-checkpoints DIR' to "
        "every child's argv — serving replicas then live-adopt the "
        "trainer's newly fleet-valid checkpoints (gate, canary, "
        "SLO-verdict promote/rollback) with no restart or recompile",
    )
    parser.add_argument(
        "--heartbeat-timeout",
        type=float,
        default=None,
        help="localhost mode: tear the fleet down when any child's "
        "heartbeat goes stale this many seconds (stalled-host "
        "detection; only for commands that initialize_from_env)",
    )
    parser.add_argument(
        "--term-grace",
        type=float,
        default=DEFAULT_TERM_GRACE_S,
        help="seconds a SIGTERM'd fleet gets to write emergency "
        f"checkpoints before SIGKILL (default {DEFAULT_TERM_GRACE_S:g})",
    )
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (append: -- python your_driver.py)")

    host, sep, port_str = args.coordinator.rpartition(":")
    if not sep or not port_str.isdigit():
        parser.error(
            f"--coordinator must be host:port, got {args.coordinator!r}"
        )

    if args.process_id is None:
        if host not in ("127.0.0.1", "localhost"):
            parser.error(
                "localhost mode spawns every process here; a non-local "
                f"--coordinator host ({host!r}) requires --process-id "
                "(run once per host)"
            )
        controller = None
        if args.autoscale_workdir:
            from distributed_tensorflow_models_tpu.serving import (
                admission as admlib,
            )

            controller = FleetAutoscaler(
                args.autoscale_workdir,
                policy=admlib.AutoscalePolicy(
                    min_replicas=args.autoscale_min,
                    max_replicas=args.autoscale_max,
                    up_backlog=args.autoscale_up_backlog,
                    down_backlog=args.autoscale_down_backlog,
                ),
                queue_dir=args.autoscale_queue_dir,
                poll_interval_s=args.autoscale_interval,
            )
        elif args.autoscale_queue_dir:
            parser.error(
                "--autoscale-queue-dir needs --autoscale-workdir"
            )
        if args.max_restarts > 0:
            return supervise_local(
                args.num_processes,
                command,
                max_restarts=args.max_restarts,
                port=int(port_str),
                resize_to=args.resize_to,
                auto_resize=args.auto_resize,
                follow_checkpoints=args.follow_checkpoints,
                cpu_devices_per_process=args.cpu_devices_per_process,
                heartbeat_timeout=args.heartbeat_timeout,
                term_grace_s=args.term_grace,
                scale_controller=controller,
            )
        if args.resize_to is not None or args.auto_resize:
            parser.error(
                "--resize-to/--auto-resize only apply to the restart "
                "loop; add --max-restarts N"
            )
        if args.follow_checkpoints:
            command = list(command) + [
                "--follow-checkpoints", args.follow_checkpoints,
            ]
        codes = launch_local(
            args.num_processes,
            command,
            port=int(port_str),
            cpu_devices_per_process=args.cpu_devices_per_process,
            heartbeat_timeout=args.heartbeat_timeout,
            term_grace_s=args.term_grace,
            scale_controller=controller,
        )
        return aggregate_exit_codes(codes)

    env = os.environ
    env[ENV_COORDINATOR] = args.coordinator
    env[ENV_NUM_PROCESSES] = str(args.num_processes)
    env[ENV_PROCESS_ID] = str(args.process_id)
    if args.cpu_devices_per_process is not None:
        env[ENV_CPU_DEVICES] = str(args.cpu_devices_per_process)
    os.execvp(command[0], command)


if __name__ == "__main__":
    sys.exit(main())
