"""Exponential moving average of parameters with TF semantics.

The reference's Inception training maintains an EMA of all trainable
variables and its eval driver restores the EMA *shadow* values in place of
the raw weights (TF moving_averages.py:284,493,638 — SURVEY.md §2.2 F14,
§3.5).  Here the shadow pytree lives inside the train state and is updated
functionally each step; "restoring shadows" at eval is just selecting
``state.ema_params`` instead of ``state.params``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

PyTree = Any


def effective_decay(decay: float, num_updates: jax.Array | None) -> jax.Array:
    """TF's warmup-damped decay (TF moving_averages.py:284): when
    ``num_updates`` is supplied, the effective decay is
    ``min(decay, (1 + num_updates) / (10 + num_updates))`` so early steps
    average faster."""
    decay = jnp.asarray(decay, jnp.float32)
    if num_updates is None:
        return decay
    n = num_updates.astype(jnp.float32)
    return jnp.minimum(decay, (1.0 + n) / (10.0 + n))


def update_ema(
    ema_params: PyTree,
    params: PyTree,
    decay: float,
    num_updates: jax.Array | None = None,
) -> PyTree:
    """``shadow <- shadow - (1 - decay) * (shadow - value)``
    (TF moving_averages.py:493 ``apply``)."""
    d = effective_decay(decay, num_updates)
    return jax.tree.map(
        lambda s, v: s - (1.0 - d) * (s - v.astype(s.dtype)),
        ema_params,
        params,
    )
