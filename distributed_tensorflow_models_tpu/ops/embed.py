"""Token embedding lookup with a selectable gradient lowering.

The forward is a plain gather — XLA lowers it well on TPU.  The
BACKWARD is the interesting half: the native vjp of ``take`` is a
scatter-add over ``B*T`` token indices, and XLA's TPU scatter is the
classic hidden cost of LM train steps (serialized row updates; the
transformer_parts ablation in bench.py exists to measure exactly this —
its ``frozen_embed`` variant removes this op from the step).  The MXU
alternative every TPU embedding implementation reaches for is the
one-hot matmul: ``dTable = one_hot(tokens)^T @ dOut`` — 2·N·V·d extra
FLOPs (~84 GFLOP at the flagship transformer config, ~0.4 ms of MXU
time) in exchange for zero scatter traffic; the one-hot is built from an
iota compare that XLA fuses into the matmul operand read, so it is
never materialized in HBM.

``grad_impl``:

- ``"scatter"`` — the native lowering (f32 accumulation), the default
  until a hardware A/B says otherwise (measured-defaults principle:
  every perf default in this repo cites a banked artifact).
- ``"matmul"`` — chunked one-hot matmul, f32 accumulation, chunked over
  the flattened token dim so the (chunk, V) one-hot stays fusion-sized.

Both accumulate in f32 and produce the same values up to f32 summation
order (pinned in tests/test_ops.py).  The trace-time env knob
``DTM_EMBED_GRAD`` selects the default for the model zoo's
:class:`TokenEmbed` (same contract as DTM_CONV_IMPL / DTM_FLASH_TILE:
invalid values fail loudly naming the knob).
"""

from __future__ import annotations

import functools
import os

import flax.linen as nn
import jax
import jax.numpy as jnp

_VALID_IMPLS = ("scatter", "matmul")


def resolve_embed_grad_impl(impl: str = "auto") -> str:
    if impl == "auto":
        impl = os.environ.get("DTM_EMBED_GRAD", "scatter")
    if impl not in _VALID_IMPLS:
        raise ValueError(
            f"embed grad impl (DTM_EMBED_GRAD) must be one of "
            f"{_VALID_IMPLS}, got {impl!r}"
        )
    return impl


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def embed_lookup(
    table: jax.Array,
    tokens: jax.Array,
    grad_impl: str = "scatter",
    chunk: int = 2048,
) -> jax.Array:
    """``table[tokens]`` with the backward lowering chosen by
    ``grad_impl`` (see module docstring).  ``tokens`` may have any
    integer shape; output shape is ``tokens.shape + (d,)``."""
    return jnp.take(table, tokens, axis=0)


def _embed_fwd(table, tokens, grad_impl, chunk):
    # Residuals must be JAX types: a (V, 0) empty array is a zero-byte
    # witness for the table's vocab size and dtype.
    witness = jnp.zeros((table.shape[0], 0), table.dtype)
    return embed_lookup(table, tokens, grad_impl, chunk), (tokens, witness)


def _embed_bwd(grad_impl, chunk, res, g):
    tokens, witness = res
    V, tdtype = witness.shape[0], witness.dtype
    d = g.shape[-1]
    flat = tokens.reshape(-1)
    gf = g.reshape(-1, d)
    n = flat.shape[0]
    if grad_impl == "scatter":
        dt = (
            jnp.zeros((V, d), jnp.float32)
            .at[flat]
            .add(gf.astype(jnp.float32))
        )
        return dt.astype(tdtype), None
    # Chunked one-hot matmul.  Padding rows carry g = 0, so whatever
    # token index they one-hot against contributes nothing.  Negative
    # ids wrap numpy-style in the forward gather (and in the scatter
    # path), so wrap them here too or the one-hot compare would silently
    # drop their gradient and the two impls would train different
    # models.  max(1, ...) keeps the empty-token edge from a
    # divide-by-zero the scatter path doesn't have.
    flat = jnp.where(flat < 0, flat + V, flat)
    chunk = max(1, min(chunk, n))
    pad = (-n) % chunk
    if pad:
        flat = jnp.pad(flat, (0, pad))
        gf = jnp.pad(gf, ((0, pad), (0, 0)))
    toks = flat.reshape(-1, chunk)
    gs = gf.reshape(-1, chunk, d)
    vocab = jax.lax.broadcasted_iota(flat.dtype, (1, V), 1)

    def body(acc, xs):
        tok_c, g_c = xs
        # One-hot in g's dtype: {0, 1} is exact in bf16, products are
        # exact, and the dot accumulates f32 — only summation ORDER
        # differs from the scatter path.
        oh = (tok_c[:, None] == vocab).astype(g_c.dtype)  # [chunk, V]
        acc = acc + jax.lax.dot_general(
            oh, g_c, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [V, d]
        return acc, None

    dt, _ = jax.lax.scan(
        body, jnp.zeros((V, d), jnp.float32), (toks, gs)
    )
    return dt.astype(tdtype), None


embed_lookup.defvjp(_embed_fwd, _embed_bwd)


class TokenEmbed(nn.Module):
    """Drop-in for ``nn.Embed`` (same param path ``<name>/embedding``,
    same default init, same dtype promotion) with the selectable
    gradient lowering.  ``grad_impl="auto"`` resolves DTM_EMBED_GRAD at
    trace time, defaulting to the native scatter."""

    num_embeddings: int
    features: int
    dtype: jnp.dtype = jnp.float32
    grad_impl: str = "auto"

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        table = self.param(
            "embedding",
            nn.initializers.variance_scaling(
                1.0, "fan_in", "normal", out_axis=0
            ),
            (self.num_embeddings, self.features),
        )
        impl = resolve_embed_grad_impl(self.grad_impl)
        return embed_lookup(
            table.astype(self.dtype), tokens, impl
        )
