"""Expert parallelism: Switch-style mixture-of-experts over the ``expert``
mesh axis.

The reference has no MoE (SURVEY.md §2.4: "out of scope") — like
``parallel/pipeline.py`` this is the framework's design-headroom layer for
the reserved ``expert`` axis, in the TPU-native form: expert FFN weights
shard one-expert-per-rank over ``expert``; tokens are exchanged with
``lax.all_to_all`` (compiled to ICI all-to-all), each rank runs its expert
on the tokens routed to it, and a second all-to-all returns them.  One
compiled SPMD program, no parameter servers, no host-side routing.

Router: top-1 ("switch") gating with a per-expert capacity.  Tokens over
capacity are *dropped* (their combine weight is zero and the residual path
carries them) — the standard Switch-Transformer trade that keeps every
shape static for XLA (SURVEY.md §7: no dynamic shapes).  The auxiliary
load-balancing loss (fraction-dispatched x mean-gate per expert, scaled by
E) is returned for the caller to add to the task loss.

Everything is differentiable: ``all_to_all`` has a transpose rule, routing
uses one-hot matmuls, and capacity masking is a multiply.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_tensorflow_models_tpu.core.mesh import AxisNames

class MoEOutput(NamedTuple):
    out: jax.Array  # [tokens, d_model] combined expert outputs
    aux_loss: jax.Array  # scalar load-balancing loss
    dropped_fraction: jax.Array  # scalar diagnostics


def init_moe_params(
    rng: jax.Array, num_experts: int, d_model: int, d_ff: int
) -> dict:
    """Per-expert FFN (w_in [E, d, f], w_out [E, f, d]) + router [d, E].
    Shard the expert-stacked leaves over ``expert`` with
    :func:`moe_param_spec`."""
    k1, k2, k3 = jax.random.split(rng, 3)
    scale_in = 1.0 / jnp.sqrt(d_model)
    scale_out = 1.0 / jnp.sqrt(d_ff)
    return {
        "router": jax.random.normal(k1, (d_model, num_experts)) * scale_in,
        "w_in": jax.random.normal(k2, (num_experts, d_model, d_ff))
        * scale_in,
        "w_out": jax.random.normal(k3, (num_experts, d_ff, d_model))
        * scale_out,
    }


def moe_param_spec(axis: str = AxisNames.EXPERT) -> dict:
    return {
        "router": P(),
        "w_in": P(axis),
        "w_out": P(axis),
    }


def _route_local(x, router, num_experts: int, capacity: int):
    """Top-1 routing of local tokens [n, d] → dispatch/combine tensors.

    Returns (dispatch [n, E, C] 0/1, combine [n, E, C] gate-weighted,
    aux_loss, dropped_fraction).  Position within an expert's capacity is
    assigned in token order (cumsum), matching the Switch reference.
    """
    n = x.shape[0]
    logits = x @ router  # [n, E] — router always in f32 for stable softmax
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)  # [n]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

    onehot = jax.nn.one_hot(expert_idx, num_experts, dtype=jnp.float32)
    # Position of each token in its expert's queue (0-based).
    position = jnp.cumsum(onehot, axis=0) * onehot - onehot  # [n, E]
    pos = jnp.sum(position, axis=-1).astype(jnp.int32)  # [n]
    # one_hot of an out-of-range pos is an all-zero row, which IS the
    # capacity mask: over-capacity tokens get a zero dispatch slot.
    pos_onehot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
    dispatch_nec = onehot[:, :, None] * pos_onehot[:, None, :]  # [n,E,C]
    combine_nec = dispatch_nec * gate[:, None, None]

    # Switch aux loss: E * sum_e fraction_tokens(e) * mean_prob(e).
    fraction = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(fraction * mean_prob)
    dropped = 1.0 - jnp.sum(dispatch_nec) / n
    return dispatch_nec, combine_nec, aux, dropped


def moe_ffn(
    params: dict,
    x: jax.Array,
    *,
    mesh: Mesh,
    capacity_factor: float = 1.25,
    axis: str = AxisNames.EXPERT,
    activation=jax.nn.relu,
) -> MoEOutput:
    """Expert-parallel Switch FFN over tokens ``x`` [tokens, d_model].

    Tokens shard over ``axis`` (each expert rank also holds a token shard
    — the standard EP layout where the same devices carry both roles);
    expert weights shard one-per-rank.  Two ``all_to_all`` collectives move
    each token to its expert and back.
    """
    num_experts = params["w_in"].shape[0]
    e_size = mesh.shape[axis]
    if num_experts % e_size:
        raise ValueError(
            f"num_experts {num_experts} not divisible by expert axis {e_size}"
        )
    tokens = x.shape[0]
    if tokens % e_size:
        raise ValueError(
            f"tokens {tokens} not divisible by expert axis {e_size}"
        )
    local_tokens = tokens // e_size
    capacity = max(
        1, int(capacity_factor * local_tokens / num_experts)
    )

    def per_device(params, x_local):
        experts_local = num_experts // e_size
        dispatch, combine, aux, dropped = _route_local(
            x_local.astype(jnp.float32),
            params["router"],
            num_experts,
            capacity,
        )
        # Gather expert inputs: [E, C, d] on the source rank...
        expert_in = jnp.einsum("nec,nd->ecd", dispatch, x_local)
        # ...reshape to [e_size, experts_local, C, d] and exchange so rank r
        # receives every source's slots for its local experts.
        expert_in = expert_in.reshape(
            e_size, experts_local, capacity, -1
        )
        recv = lax.all_to_all(
            expert_in, axis, split_axis=0, concat_axis=0, tiled=False
        )  # [e_size(source), experts_local, C, d]

        w_in = params["w_in"]  # [experts_local, d, f] (sharded slice)
        w_out = params["w_out"]
        h = activation(jnp.einsum("slcd,ldf->slcf", recv, w_in))
        expert_out = jnp.einsum("slcf,lfd->slcd", h, w_out)

        # Send results back to their source ranks.
        back = lax.all_to_all(
            expert_out, axis, split_axis=0, concat_axis=0, tiled=False
        )  # [e_size(expert-group), experts_local, C, d]
        back = back.reshape(num_experts, capacity, -1)
        out = jnp.einsum("nec,ecd->nd", combine, back)
        aux = lax.pmean(aux, axis)
        dropped = lax.pmean(dropped, axis)
        return out.astype(x_local.dtype), aux, dropped

    fn = jax.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(moe_param_spec(axis), P(axis)),
        out_specs=(P(axis), P(), P()),
    )
    out, aux, dropped = fn(params, x)
    return MoEOutput(out=out, aux_loss=aux, dropped_fraction=dropped)


def moe_ffn_reference(
    params: dict,
    x: jax.Array,
    *,
    num_ranks: int,
    capacity_factor: float = 1.25,
    activation=jax.nn.relu,
) -> MoEOutput:
    """Single-device oracle with identical routing/capacity semantics
    (including the per-source-rank capacity accounting EP implies):
    processes the token shards rank-by-rank exactly as the EP layout
    would."""
    num_experts = params["w_in"].shape[0]
    tokens = x.shape[0]
    if tokens % num_ranks:
        raise ValueError(
            f"tokens {tokens} not divisible by num_ranks {num_ranks}"
        )
    local_tokens = tokens // num_ranks
    capacity = max(1, int(capacity_factor * local_tokens / num_experts))

    outs, auxes, drops = [], [], []
    for r in range(num_ranks):
        xl = x[r * local_tokens : (r + 1) * local_tokens].astype(
            jnp.float32
        )
        dispatch, combine, aux, dropped = _route_local(
            xl, params["router"], num_experts, capacity
        )
        expert_in = jnp.einsum("nec,nd->ecd", dispatch, xl)
        h = activation(
            jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"])
        )
        expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_out"])
        outs.append(
            jnp.einsum("nec,ecd->nd", combine, expert_out).astype(x.dtype)
        )
        auxes.append(aux)
        drops.append(dropped)
    return MoEOutput(
        out=jnp.concatenate(outs, axis=0),
        aux_loss=jnp.mean(jnp.stack(auxes)),
        dropped_fraction=jnp.mean(jnp.stack(drops)),
    )
