"""Eval drivers: checkpoint-restoring top-1/top-5 (and perplexity) loops.

Reference semantics (SURVEY.md §3.5): the eval process restores the newest
checkpoint — EMA *shadow* variables when the model maintains them (TF
moving_averages.py:638) — runs top-1/top-5 counts over the validation set,
and optionally repeats every N minutes on the newest checkpoint
(``--run_once`` flag in the inception eval driver).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Optional

import jax
import numpy as np

from distributed_tensorflow_models_tpu.core import sharding
from distributed_tensorflow_models_tpu.core import train_loop
from distributed_tensorflow_models_tpu.harness import checkpoint as ckptlib
from distributed_tensorflow_models_tpu.harness import train as trainlib
from distributed_tensorflow_models_tpu.harness.config import ExperimentConfig
from distributed_tensorflow_models_tpu.ops import losses as losslib

log = logging.getLogger("dtm")


@dataclasses.dataclass
class EvalResult:
    step: int
    metrics: dict


def evaluate_classification(
    cfg: ExperimentConfig,
    workdir: str,
    *,
    mesh=None,
    max_batches: Optional[int] = None,
    use_ema: bool = True,
) -> EvalResult:
    """One eval pass at the latest checkpoint: top-1/top-5 over the
    validation split (counting scheme of the reference's eval loop)."""
    if mesh is None:
        mesh = trainlib.mesh_from_config(cfg)
    template = trainlib.build_state(cfg, mesh)
    manager = ckptlib.CheckpointManager(workdir, keep=cfg.keep_checkpoints)
    state, _ = manager.restore(template)
    state = train_loop.place_state(state, mesh)
    eval_step = train_loop.make_eval_step(
        state.apply_fn, use_ema=use_ema and state.ema_params is not None
    )

    dataset = trainlib.build_dataset(cfg, "test")
    max_batches = max_batches or cfg.eval_batches
    if max_batches is None:
        # One pass over the validation set.  Epoch-looping datasets
        # (ArrayDataset) expose batches_per_epoch; one-pass datasets
        # (eval TFRecord) terminate on their own.
        max_batches = getattr(dataset, "batches_per_epoch", None)
    top1 = top5 = count = xent = 0.0
    for i, batch in enumerate(dataset):
        if max_batches is not None and i >= max_batches:
            break
        if len(batch["label"]) % mesh.devices.size:
            # Partial final batch: pad to the mesh and mask via counts.
            batch = _pad_batch(batch, mesh.devices.size)
        out = eval_step(state, sharding.shard_batch(mesh, batch))
        top1 += float(out["top1_count"])
        top5 += float(out["top5_count"])
        count += float(out["count"])
        xent += float(out["xent_sum"])
    manager.close()
    metrics = {
        "top1": top1 / max(count, 1),
        "top5": top5 / max(count, 1),
        "xent": xent / max(count, 1),
        "count": count,
    }
    log.info(
        "eval @ step %d: top1=%.4f top5=%.4f over %d examples",
        int(state.step), metrics["top1"], metrics["top5"], int(count),
    )
    return EvalResult(step=int(state.step), metrics=metrics)


def _pad_batch(batch, multiple: int):
    """Pad with copies of row 0, tagging padding with label -1 so top-k
    counts ignore it (label -1 matches nothing)."""
    n = len(batch["label"])
    pad = (-n) % multiple
    if pad == 0:
        return batch
    out = {}
    for k, v in batch.items():
        pad_rows = np.repeat(v[:1], pad, axis=0)
        if k == "label":
            pad_rows = np.full((pad,), -1, v.dtype)
        out[k] = np.concatenate([v, pad_rows], axis=0)
    return out


def evaluate_lm(
    cfg: ExperimentConfig,
    workdir: str,
    *,
    mesh=None,
    max_batches: Optional[int] = None,
) -> EvalResult:
    """Perplexity over the validation stream (R8's ``run_epoch`` eval):
    fresh zero carry, threaded across the whole split, ppl = exp(mean nll)."""
    if mesh is None:
        mesh = trainlib.mesh_from_config(cfg)
    template = trainlib.build_state(cfg, mesh)
    manager = ckptlib.CheckpointManager(workdir, keep=cfg.keep_checkpoints)
    state, _ = manager.restore(template)
    state = train_loop.place_state(state, mesh)

    @jax.jit
    def lm_eval_step(state, carry, batch):
        logits, new_carry = state.apply_fn(
            {"params": state.eval_params}, batch["inputs"], carry=carry,
            train=False,
        )
        nll = losslib.softmax_cross_entropy(logits, batch["targets"])
        return new_carry, nll.sum(), np.prod(batch["targets"].shape).astype(
            np.float32
        )

    dataset = trainlib.build_dataset(cfg, "valid")
    carry = template.carry  # zero carry from the fresh template
    total_nll = total_tok = 0.0
    n_batches = dataset.batches_per_epoch
    if max_batches is not None:
        n_batches = min(n_batches, max_batches)
    it = iter(dataset)
    for _ in range(n_batches):
        batch = sharding.shard_batch(mesh, next(it))
        carry, nll_sum, n_tok = lm_eval_step(state, carry, batch)
        total_nll += float(nll_sum)
        total_tok += float(n_tok)
    manager.close()
    ppl = float(np.exp(total_nll / max(total_tok, 1)))
    metrics = {"perplexity": ppl, "nll": total_nll / max(total_tok, 1)}
    log.info("eval @ step %d: perplexity=%.2f", int(state.step), ppl)
    return EvalResult(step=int(state.step), metrics=metrics)


def continuous_eval(
    cfg: ExperimentConfig,
    workdir: str,
    *,
    interval_secs: float = 60.0,
    max_evals: Optional[int] = None,
    max_batches: Optional[int] = None,
):
    """Re-evaluate whenever a new checkpoint appears — the reference's
    repeat-every-N-minutes eval loop (SURVEY.md §3.5 last line).  Yields
    :class:`EvalResult` per new checkpoint."""
    seen: Optional[int] = None
    evals = 0
    manager = ckptlib.CheckpointManager(workdir, keep=cfg.keep_checkpoints)
    while max_evals is None or evals < max_evals:
        latest = manager.latest_step()
        if latest is not None and latest != seen:
            seen = latest
            fn = evaluate_lm if cfg.task == "lm" else evaluate_classification
            yield fn(cfg, workdir, max_batches=max_batches)
            evals += 1
        else:
            time.sleep(interval_secs)
