"""Helper twin: non-blocking drain."""
import queue

_Q = queue.Queue()


def drain_one():
    try:
        return _Q.get_nowait()
    except queue.Empty:
        return None
