"""Telemetry subsystem: registry semantics, hot-loop overhead guard,
instrumented-step compile accounting, pipeline instrumentation, the
TelemetryHook injection/aggregation, the goodput report, and the
end-to-end smoke run whose artifacts the schema lint validates."""

import json
import math
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_models_tpu import telemetry
from distributed_tensorflow_models_tpu.core import train_loop
from distributed_tensorflow_models_tpu.harness import (
    config as configlib,
    hooks as hooklib,
    train as trainlib,
)

SCHEMA_LINT = os.path.join(
    os.path.dirname(__file__), "..", "scripts", "check_metrics_schema.py"
)


# --------------------------------------------------------------------------
# Registry
# --------------------------------------------------------------------------


def test_counter_gauge_timer_snapshot():
    reg = telemetry.MetricsRegistry()
    reg.counter("events").inc()
    reg.counter("events").inc(2.5)
    reg.gauge("depth").set(3)
    t = reg.timer("lap")
    for v in (0.1, 0.2, 0.3, 0.4):
        t.record(v)
    snap = reg.snapshot()
    assert snap["events"] == 3.5
    assert snap["depth"] == 3.0
    assert snap["lap/count"] == 4
    assert snap["lap/total_s"] == pytest.approx(1.0)
    assert snap["lap/mean_s"] == pytest.approx(0.25)
    assert snap["lap/max_s"] == pytest.approx(0.4)
    assert snap["lap/p50_s"] == pytest.approx(0.3)  # nearest-rank
    assert snap["lap/p95_s"] == pytest.approx(0.4)
    assert snap["lap/p99_s"] == pytest.approx(0.4)


def test_timer_reservoir_ages_out_old_samples():
    t = telemetry.Timer()
    for _ in range(telemetry.Timer.RESERVOIR):
        t.record(100.0)  # warmup-era outliers
    for _ in range(telemetry.Timer.RESERVOIR):
        t.record(0.001)  # steady state overwrites the ring
    (p95,) = t.percentiles(0.95)
    assert p95 == pytest.approx(0.001)  # outliers aged out of p95...
    assert t.max == 100.0  # ...but the all-time max survives


def test_span_records_on_error_too():
    reg = telemetry.MetricsRegistry()
    with pytest.raises(ValueError):
        with reg.span("work"):
            raise ValueError("boom")
    assert reg.snapshot()["work/count"] == 1


def test_registries_are_isolated():
    a, b = telemetry.MetricsRegistry(), telemetry.MetricsRegistry()
    a.counter("x").inc()
    assert "x" not in b.snapshot()
    assert telemetry.get_registry() is telemetry.get_registry()


# --------------------------------------------------------------------------
# Overhead guard (tier-1 CI): per-step telemetry cost on a hot loop
# --------------------------------------------------------------------------


def test_hot_loop_overhead_under_5us_per_step():
    """The full per-step recording set (one timer record, one counter inc,
    one gauge set) plus a snapshot every 100 steps — the real cadence —
    must amortize under 5 µs/step on CPU, or telemetry would tax the very
    step time it measures.  Tracing rides the SAME budget: the loop runs
    with a live tracer at the default ring size and adds the per-step
    trace events fit's hot path produces — the data-wait span's trace
    mirror (what ``registry.span`` emits beyond the timer record already
    counted here) and the per-chunk ``train/chunk`` complete event — so
    the flight recorder cannot quietly re-tax the step path."""
    reg = telemetry.MetricsRegistry()
    reg.trace = telemetry.Tracer(
        capacity=configlib.ExperimentConfig.trace_ring_events
    )
    t = reg.timer(telemetry.STEP_TIME)
    c = reg.counter("steps")
    g = reg.gauge(telemetry.HOST_QUEUE_DEPTH)
    # Populate a realistic snapshot surface first.
    for name in (telemetry.DATA_WAIT, telemetry.DISPATCH,
                 telemetry.PREFETCH_FILL, telemetry.CKPT_SAVE):
        reg.timer(name).record(0.01)
    N = 20_000
    best = float("inf")
    for _ in range(3):  # best-of-3 shields against CI scheduler noise
        t0 = time.perf_counter()
        for i in range(N):
            t.record(1e-4)
            c.inc()
            g.set(i & 7)
            # The span's trace-emission increment (its timer record is
            # the t.record above) + the per-chunk event, args included.
            reg.trace.complete(telemetry.DATA_WAIT, 1e-4)
            reg.trace.complete(
                "train/chunk", 1e-4, args={"start": i, "k": 1}
            )
            if i % 100 == 0:
                reg.snapshot()
        best = min(best, (time.perf_counter() - t0) / N)
    assert reg.trace.emitted == 3 * 2 * N  # both sites really traced
    assert best < 5e-6, f"telemetry hot-loop cost {best*1e6:.2f} µs/step"


# --------------------------------------------------------------------------
# InstrumentedStep: compile events + FLOPs
# --------------------------------------------------------------------------


def test_instrumented_step_counts_compiles_and_flops():
    reg = telemetry.MetricsRegistry()
    jitted = jax.jit(
        lambda s, b, r: (s + b["x"].sum(), {"loss": b["x"].sum()})
    )
    istep = train_loop.InstrumentedStep(jitted, registry=reg)
    s = jnp.float32(0.0)
    rng = jax.random.key(0)
    for _ in range(3):
        s, m = istep(s, {"x": jnp.ones((64, 64))}, rng)
    snap = reg.snapshot()
    assert snap[f"{telemetry.COMPILE}/count"] == 1  # same signature: cached
    # First call compiled (recorded as a compile event, not a dispatch);
    # the two cache hits are dispatches.
    assert snap[f"{telemetry.DISPATCH}/count"] == 2
    assert snap[f"{telemetry.COMPILE}/total_s"] > 0
    # XLA cost analysis is available on CPU: the FLOPs gauge must be live.
    assert snap[telemetry.FLOPS_PER_STEP] > 0
    assert istep.flops_per_step == snap[telemetry.FLOPS_PER_STEP]

    # New batch signature -> a recorded recompile event.
    s2, _ = istep(jnp.float32(0.0), {"x": jnp.ones((32, 32))}, rng)
    assert reg.snapshot()[f"{telemetry.COMPILE}/count"] == 2
    assert float(s2) == pytest.approx(32 * 32)


def test_instrumented_step_flops_total_weights_mixed_signatures():
    """A ragged (smaller) batch must add *its own* program's FLOPs to the
    retired-FLOPs counter, not re-price the whole run (the MFU numerator
    is the counter, never gauge x steps)."""
    reg = telemetry.MetricsRegistry()
    jitted = jax.jit(lambda s, b, r: (s, {"loss": (b["x"] @ b["x"]).sum()}))
    istep = train_loop.InstrumentedStep(jitted, registry=reg)
    full = {"x": jnp.ones((64, 64))}
    ragged = {"x": jnp.ones((16, 16))}
    istep(0.0, full, None)
    f_full = reg.snapshot()[telemetry.FLOPS_TOTAL]
    assert f_full > 0
    istep(0.0, full, None)
    assert reg.snapshot()[telemetry.FLOPS_TOTAL] == pytest.approx(2 * f_full)
    istep(0.0, ragged, None)
    f_ragged = reg.snapshot()[telemetry.FLOPS_TOTAL] - 2 * f_full
    assert 0 < f_ragged < f_full  # priced at the small program's cost
    istep(0.0, full, None)  # back to the full program: full price again
    assert reg.snapshot()[telemetry.FLOPS_TOTAL] == pytest.approx(
        3 * f_full + f_ragged
    )


def test_instrumented_step_falls_back_on_plain_callable():
    """A non-jitted step (no .lower, no compile cache) must still run;
    FLOPs/compile accounting degrades to nothing, dispatch still ticks."""
    reg = telemetry.MetricsRegistry()
    istep = train_loop.InstrumentedStep(
        lambda s, b, r: (s + 1, {"loss": 0.0}), registry=reg
    )
    s, _ = istep(0, {"x": np.ones((2,))}, None)
    s, _ = istep(s, {"x": np.ones((2,))}, None)
    assert s == 2
    snap = reg.snapshot()
    assert snap[f"{telemetry.DISPATCH}/count"] == 2
    assert snap.get(f"{telemetry.COMPILE}/count", 0.0) == 0


def test_instrumented_step_tolerates_resharded_state(mesh8):
    """The TP-resume regression guard: a state resharded between calls
    (as checkpoint restore + place_state produces) must run through the
    wrapper — plain-jit resharding semantics, with the recompile showing
    up as a second compile event."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    reg = telemetry.MetricsRegistry()
    jitted = jax.jit(lambda s, b, r: (s * 1.0 + b["x"].sum(), {}))
    istep = train_loop.InstrumentedStep(jitted, registry=reg)
    batch = {"x": jnp.ones((8,))}
    s = jax.device_put(
        jnp.zeros((8, 4)), NamedSharding(mesh8, P("data", None))
    )
    s, _ = istep(s, batch, None)
    # Re-lay the carry out differently (replicated), as a restore would.
    s = jax.device_put(np.asarray(s), NamedSharding(mesh8, P()))
    s, _ = istep(s, batch, None)
    assert reg.snapshot()[f"{telemetry.COMPILE}/count"] == 2


# --------------------------------------------------------------------------
# Pipeline instrumentation
# --------------------------------------------------------------------------


def test_pipeline_records_waits_and_depths(mesh8):
    from distributed_tensorflow_models_tpu.data import datasets, pipeline

    reg = telemetry.MetricsRegistry()
    x = np.arange(64, dtype=np.float32).reshape(64, 1)
    y = np.arange(64, dtype=np.int32)
    ds = datasets.ArrayDataset({"image": x, "label": y}, 8, seed=0)
    host = pipeline.HostPipeline(ds, prefetch=2, registry=reg)
    pre = pipeline.DevicePrefetcher(host, mesh8, depth=2, registry=reg)
    for _ in range(3):
        next(pre)
    snap = reg.snapshot()
    # Prefetcher pulled >= depth + consumed batches from upstream.
    assert snap[f"{telemetry.PREFETCH_FILL}/count"] >= 3
    assert snap[telemetry.PREFETCH_DEPTH] >= 1
    # The producer thread recorded put waits and the queue depth gauge.
    assert snap[f"{telemetry.PRODUCER_WAIT}/count"] >= 1
    assert telemetry.HOST_QUEUE_DEPTH in snap
    host.stop()


# --------------------------------------------------------------------------
# TelemetryHook
# --------------------------------------------------------------------------


class _FakeState:
    step = jnp.asarray(0)


def test_telemetry_hook_injects_at_cadence_only():
    reg = telemetry.MetricsRegistry()
    h = hooklib.TelemetryHook(reg, every_steps=2)
    h.begin(_FakeState())
    reg.timer(telemetry.STEP_TIME).record(0.02)
    reg.timer(telemetry.DATA_WAIT).record(0.01)
    metrics = {"loss": 1.0}
    h.after_step(_FakeState(), metrics, 1)
    assert "data_wait_s" not in metrics  # off-cadence: untouched
    h.after_step(_FakeState(), metrics, 2)
    for key in ("data_wait_s", "step_time_s", "mfu", "steps_per_sec",
                "stall_fraction", "compile_count", "checkpoint_s"):
        assert key in metrics, key
    assert metrics["step_time_s"] == pytest.approx(0.02)
    assert metrics["data_wait_s"] == pytest.approx(0.01 / 2)


def test_telemetry_hook_interval_deltas_reset():
    """Second firing must report the new interval, not cumulative sums."""
    reg = telemetry.MetricsRegistry()
    h = hooklib.TelemetryHook(reg, every_steps=1)
    h.begin(_FakeState())
    reg.timer(telemetry.STEP_TIME).record(0.5)
    m1 = {}
    h.after_step(_FakeState(), m1, 1)
    reg.timer(telemetry.STEP_TIME).record(0.1)
    m2 = {}
    h.after_step(_FakeState(), m2, 2)
    assert m1["step_time_s"] == pytest.approx(0.5)
    assert m2["step_time_s"] == pytest.approx(0.1)


def test_telemetry_hook_multihost_aggregation(monkeypatch):
    """Chief-side cross-host view: allgathered steps/sec + stall fraction
    (process_allgather monkeypatched — no real cluster in CI)."""
    from jax.experimental import multihost_utils

    def fake_allgather(arr):
        return np.stack([arr, arr * 3.0])  # "other host" is 3x

    monkeypatch.setattr(
        multihost_utils, "process_allgather", fake_allgather
    )
    reg = telemetry.MetricsRegistry()
    h = hooklib.TelemetryHook(reg, every_steps=1, process_count=2)
    h.begin(_FakeState())
    reg.timer(telemetry.DATA_WAIT).record(0.001)
    metrics = {}
    h.after_step(_FakeState(), metrics, 1)
    assert metrics["hosts/steps_per_sec_mean"] == pytest.approx(
        2.0 * metrics["hosts/steps_per_sec_min"]
    )
    assert metrics["hosts/stall_fraction_max"] == pytest.approx(
        3.0 * metrics["stall_fraction"], rel=1e-5
    )


# --------------------------------------------------------------------------
# Goodput report
# --------------------------------------------------------------------------


def test_goodput_report_fractions_sum_to_one(tmp_path):
    reg = telemetry.MetricsRegistry()
    reg.timer(telemetry.DATA_WAIT).record(0.2)
    reg.timer(telemetry.CKPT_SAVE).record(0.05)
    reg.timer(telemetry.CKPT_WAIT).record(0.05)
    reg.timer(telemetry.COMPILE).record(0.3)
    rep = telemetry.goodput_report(reg, total_s=1.0, steps=10, kind="CPU")
    f = rep["fractions"]
    assert sum(f.values()) == pytest.approx(1.0)
    assert f["data_stall"] == pytest.approx(0.2)
    assert f["checkpoint"] == pytest.approx(0.1)
    assert f["compile"] == pytest.approx(0.3)
    assert f["compute"] == pytest.approx(0.4)
    assert rep["steps"] == 10 and rep["compile_events"] == 1
    assert rep["mfu"] == 0.0  # no peak table entry for CPU

    path = str(tmp_path / "telemetry.json")
    telemetry.write_report(path, rep)
    assert json.load(open(path))["fractions"]["compute"] == pytest.approx(0.4)


def test_goodput_report_clamps_overattribution():
    """Attributed > total (span clock skew) must not yield negative
    compute or fractions summing past 1."""
    reg = telemetry.MetricsRegistry()
    reg.timer(telemetry.DATA_WAIT).record(2.0)
    rep = telemetry.goodput_report(reg, total_s=1.0, steps=1, kind=None)
    assert rep["fractions"]["compute"] == 0.0
    assert sum(rep["fractions"].values()) == pytest.approx(1.0)


def test_mfu_scales_by_device_count():
    """The FLOPs numerator is the GLOBAL program's cost, so MFU must
    divide by per-chip peak x mesh size — not report >100% on any
    multi-chip mesh (the bench.py global/per-chip convention)."""
    reg = telemetry.MetricsRegistry()
    reg.counter(telemetry.FLOPS_TOTAL).inc(197e12)  # one chip-second of v5e
    rep1 = telemetry.goodput_report(
        reg, total_s=1.0, steps=1, kind="TPU v5e", n_devices=1
    )
    rep4 = telemetry.goodput_report(
        reg, total_s=1.0, steps=1, kind="TPU v5e", n_devices=4
    )
    assert rep1["mfu"] == pytest.approx(1.0)
    assert rep4["mfu"] == pytest.approx(0.25)
    assert rep4["n_devices"] == 4


def test_peak_flops_lookup(monkeypatch):
    assert telemetry.peak_flops("TPU v5e") == 197e12
    assert telemetry.peak_flops("TPU v4 lite") == 275e12
    assert telemetry.peak_flops("cpu") is None
    assert telemetry.peak_flops(None) is None
    monkeypatch.setenv("DTM_PEAK_FLOPS", "1e12")
    assert telemetry.peak_flops("anything") == 1e12


# --------------------------------------------------------------------------
# End-to-end smoke (the ISSUE acceptance run) + schema lint wiring
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_smoke_train_produces_telemetry_artifacts(mesh8, tmp_path):
    """LeNet ~50 CPU steps: telemetry.json fractions sum to ~1.0, and
    metrics.jsonl carries data_wait_s / step_time_s / mfu at the logging
    cadence; the schema lint passes with --require-telemetry."""
    cfg = configlib.get_config(
        "lenet_mnist",
        train_steps=50,
        global_batch_size=32,
        log_every_steps=10,
        checkpoint_every_secs=10_000.0,
        trace_export=True,
    )
    trainlib.fit(cfg, str(tmp_path), mesh=mesh8)

    report = json.load(open(tmp_path / "telemetry.json"))
    f = report["fractions"]
    assert set(f) == {"compute", "data_stall", "checkpoint", "compile"}
    assert sum(f.values()) == pytest.approx(1.0, abs=1e-6)
    assert all(v >= 0 for v in f.values())
    assert report["steps"] == 50
    assert report["compile_events"] >= 1
    assert report["seconds"]["compile"] > 0
    assert report["seconds"]["checkpoint"] > 0  # CheckpointHook.end saved
    assert report["flops_per_step"] > 0  # XLA cost analysis on CPU
    assert math.isfinite(report["steps_per_sec"])

    rows = [
        json.loads(line)
        for line in (tmp_path / "metrics.jsonl").read_text().splitlines()
    ]
    telem_rows = [r for r in rows if "data_wait_s" in r]
    assert [r["step"] for r in telem_rows] == [10, 20, 30, 40, 50]
    for r in telem_rows:
        for key in ("data_wait_s", "step_time_s", "mfu", "steps_per_sec",
                    "stall_fraction", "compile_count"):
            assert key in r, key
        assert r["step_time_s"] > 0
        assert r["loss"] > 0  # device metrics share the row

    # The CI lint is the same script an operator runs by hand.
    proc = subprocess.run(
        [sys.executable, SCHEMA_LINT, str(tmp_path / "metrics.jsonl"),
         "--require-telemetry"],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stderr

    # Declared-vs-emitted coverage: every key constant in the telemetry
    # registry must show up in this run's snapshot, except the
    # explicitly feature/topology-gated ones (no chaos, no fleet
    # supervisor, no sharded workers, no restore, no watchdog, no
    # serving traffic here).  serve/ is NOT a blanket hole in coverage:
    # test_serving runs --declared-coverage --only-prefix serve/
    # against a served-traffic serving_stats report, so together the
    # two checks tile the whole registry.
    registry_py = os.path.join(
        os.path.dirname(SCHEMA_LINT), "..",
        "distributed_tensorflow_models_tpu", "telemetry", "registry.py",
    )
    proc = subprocess.run(
        [sys.executable, SCHEMA_LINT, str(tmp_path / "telemetry.json"),
         "--declared-coverage", registry_py,
         "--allow-missing", "chaos/",
         "--allow-missing", "fleet/",
         "--allow-missing", "checkpoint/restore",
         "--allow-missing", "pipeline/reassembly_wait",
         "--allow-missing", "pipeline/worker_busy",
         "--allow-missing", "train/watchdog_last_progress_s",
         "--allow-missing", "serve/"],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stderr + proc.stdout

    # Event tracing (default ring) leaves its accounting in the report
    # and — with trace_export on — a Perfetto-loadable per-process
    # trace; a CLEAN exit leaves no flight-recorder dump.
    snap = report["metrics"]
    assert snap["trace/events"] > 0
    assert snap["trace/dropped"] >= 0
    trace = json.load(open(tmp_path / "trace_p0.json"))
    names = {e["name"] for e in trace["traceEvents"]}
    for expected in ("fit/entry", "fit/end", "train/chunk",
                     "train/compile", "checkpoint/save"):
        assert expected in names, expected
    assert not os.path.exists(tmp_path / "flight_recorder_p0.json")


def test_schema_lint_catches_violations(tmp_path):
    from importlib import util as importutil

    spec = importutil.spec_from_file_location("check_metrics_schema",
                                             SCHEMA_LINT)
    mod = importutil.module_from_spec(spec)
    spec.loader.exec_module(mod)

    good = [json.dumps({"step": 1, "time": 1.0, "loss": 0.5}),
            json.dumps({"step": 2, "time": 2.0, "loss": 0.4,
                        "data_wait_s": 0.0, "step_time_s": 0.01,
                        "mfu": 0.0})]
    errors, rows, trows = mod.check_lines(good)
    assert not errors and rows == 2 and trows == 1

    bad = [
        "not json",
        json.dumps({"time": 1.0}),  # missing step
        json.dumps({"step": 5, "time": 1.0}),
        json.dumps({"step": 3, "time": 1.0}),  # step regression
        json.dumps({"step": 6, "time": 1.0, "tag": "oops"}),  # non-number
        json.dumps({"step": 7, "time": 1.0, "mfu": 0.1}),  # partial telem
    ]
    # Default: the regression is tolerated (recoverable_fit restarts
    # legitimately rewind the step); --strict-monotonic flags it.
    errors, _, _ = mod.check_lines(bad)
    assert len(errors) == 4
    errors, _, _ = mod.check_lines(bad, strict_monotonic=True)
    assert len(errors) == 5
    # CLI exit codes: 1 on violations, 0 on a clean file.
    p = tmp_path / "bad.jsonl"
    p.write_text("\n".join(bad) + "\n")
    assert mod.main([str(p)]) == 1
    p2 = tmp_path / "good.jsonl"
    p2.write_text("\n".join(good) + "\n")
    assert mod.main([str(p2), "--require-telemetry"]) == 0
