"""Image decode + augmentation, transform-for-transform with the reference.

The reference's input augmentation runs as C++ TF kernels inside the graph
(SURVEY.md §3.4): ``decode_jpeg`` → ``sample_distorted_bounding_box`` crop →
resize → ``random_flip_left_right`` → color distortion (inception path,
SURVEY.md §2.1 R9), and pad+random-crop+flip+``per_image_standardization``
for CIFAR (R4).  Accuracy parity depends on replicating these details
(SURVEY.md §7.4.3: "augmentation details move final top-1 by >1%").

Here they are host-side NumPy per-image transforms (the same host-CPU role
the TF kernels played), driven by a ``numpy.random.Generator`` so the
pipeline is deterministic and checkpointable.  Batched JAX variants of the
cheap transforms are provided for optional on-device augmentation.
"""

from __future__ import annotations

import io
from typing import Optional

import numpy as np


# --------------------------------------------------------------------------
# Decode
# --------------------------------------------------------------------------


def decode_jpeg(data: bytes) -> np.ndarray:
    """JPEG bytes → uint8 HWC RGB (the ``decode_jpeg`` kernel's role,
    TF gen_image_ops.py:1126)."""
    from PIL import Image

    img = Image.open(io.BytesIO(data))
    if img.mode != "RGB":
        img = img.convert("RGB")
    return np.asarray(img, dtype=np.uint8)


def encode_jpeg(img: np.ndarray, quality: int = 90) -> bytes:
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="JPEG", quality=quality)
    return buf.getvalue()


def resize_bilinear(img: np.ndarray, height: int, width: int) -> np.ndarray:
    """Bilinear resize to ``[height, width]`` (float32 output)."""
    import cv2

    out = cv2.resize(
        img.astype(np.float32), (width, height), interpolation=cv2.INTER_LINEAR
    )
    if out.ndim == 2:
        out = out[:, :, None]
    return out


# --------------------------------------------------------------------------
# Shared primitives
# --------------------------------------------------------------------------


def per_image_standardization(img: np.ndarray) -> np.ndarray:
    """``(x - mean) / max(stddev, 1/sqrt(N))`` — exact
    ``tf.image.per_image_standardization`` semantics (CIFAR path, R4)."""
    x = img.astype(np.float32)
    mean = x.mean()
    std = max(x.std(), 1.0 / np.sqrt(x.size))
    return (x - mean) / std


def random_flip_left_right(
    img: np.ndarray, rng: np.random.Generator
) -> np.ndarray:
    return img[:, ::-1] if rng.random() < 0.5 else img


def random_crop(
    img: np.ndarray, rng: np.random.Generator, height: int, width: int
) -> np.ndarray:
    h, w = img.shape[:2]
    if h < height or w < width:
        raise ValueError(f"cannot crop {height}x{width} from {h}x{w}")
    top = int(rng.integers(0, h - height + 1))
    left = int(rng.integers(0, w - width + 1))
    return img[top : top + height, left : left + width]


def central_crop(img: np.ndarray, fraction: float) -> np.ndarray:
    """``tf.image.central_crop`` — the inception eval path's 87.5% crop."""
    h, w = img.shape[:2]
    ch = int(np.floor(h * fraction))
    cw = int(np.floor(w * fraction))
    top = (h - ch) // 2
    left = (w - cw) // 2
    return img[top : top + ch, left : left + cw]


# --------------------------------------------------------------------------
# CIFAR-10 (R4): pad 4 → random 32x32 crop → flip → standardize
# --------------------------------------------------------------------------


def preprocess_cifar_train(
    img: np.ndarray, rng: np.random.Generator, pad: int = 4
) -> np.ndarray:
    padded = np.pad(
        img, ((pad, pad), (pad, pad), (0, 0)), mode="constant"
    )
    out = random_crop(padded, rng, img.shape[0], img.shape[1])
    out = random_flip_left_right(out, rng)
    return per_image_standardization(out)


def preprocess_cifar_eval(img: np.ndarray) -> np.ndarray:
    return per_image_standardization(img)


# --------------------------------------------------------------------------
# ImageNet / inception preprocessing (R9)
# --------------------------------------------------------------------------


def sample_distorted_bounding_box(
    shape: tuple[int, int],
    rng: np.random.Generator,
    *,
    bbox: Optional[np.ndarray] = None,
    min_object_covered: float = 0.1,
    aspect_ratio_range: tuple[float, float] = (0.75, 1.33),
    area_range: tuple[float, float] = (0.05, 1.0),
    max_attempts: int = 100,
) -> tuple[int, int, int, int]:
    """Sample a crop window ``(top, left, height, width)``.

    Reimplements the ``sample_distorted_bounding_box`` kernel's algorithm
    (TF image_ops_impl.py:386 binding; inception's distorted-crop, R9):
    draw an aspect ratio and an area fraction uniformly, derive the window,
    accept the first window that fits and covers ``min_object_covered`` of
    the object bbox; fall back to the whole image after ``max_attempts``.

    ``bbox`` is ``[ymin, xmin, ymax, xmax]`` in [0,1] coordinates, or None
    for "use whole image" (the reference's path for label-only records).
    """
    height, width = shape
    if bbox is None:
        bbox = np.array([0.0, 0.0, 1.0, 1.0], np.float32)
    for _ in range(max_attempts):
        aspect = rng.uniform(*aspect_ratio_range)
        area_frac = rng.uniform(*area_range)
        target_area = area_frac * height * width
        w = int(round(np.sqrt(target_area * aspect)))
        h = int(round(np.sqrt(target_area / aspect)))
        if w > width or h > height or w <= 0 or h <= 0:
            continue
        top = int(rng.integers(0, height - h + 1))
        left = int(rng.integers(0, width - w + 1))
        # Object coverage: fraction of the bbox area inside the window.
        by0, bx0, by1, bx1 = (
            bbox[0] * height,
            bbox[1] * width,
            bbox[2] * height,
            bbox[3] * width,
        )
        inter_h = max(0.0, min(top + h, by1) - max(top, by0))
        inter_w = max(0.0, min(left + w, bx1) - max(left, bx0))
        bbox_area = max((by1 - by0) * (bx1 - bx0), 1e-6)
        if inter_h * inter_w / bbox_area >= min_object_covered:
            return top, left, h, w
    return 0, 0, height, width


def _rgb_to_hsv(x: np.ndarray) -> np.ndarray:
    import cv2

    return cv2.cvtColor(x.astype(np.float32), cv2.COLOR_RGB2HSV)


def _hsv_to_rgb(x: np.ndarray) -> np.ndarray:
    import cv2

    return cv2.cvtColor(x.astype(np.float32), cv2.COLOR_HSV2RGB)


def distort_color(
    img: np.ndarray, rng: np.random.Generator, ordering: int = 0
) -> np.ndarray:
    """Inception color distortion on a float image in [0, 1].

    Two operation orderings as in the reference's ``distort_color`` (R9;
    thread-id-parity trick in the original), brightness delta 32/255,
    saturation/contrast in [0.5, 1.5], hue delta 0.2 rad.  Output clipped
    to [0, 1] as TF does.
    """

    def brightness(x):
        return x + rng.uniform(-32.0 / 255.0, 32.0 / 255.0)

    def saturation(x):
        hsv = _rgb_to_hsv(np.clip(x, 0, 1))
        hsv[..., 1] = np.clip(hsv[..., 1] * rng.uniform(0.5, 1.5), 0, 1)
        return _hsv_to_rgb(hsv)

    def hue(x):
        hsv = _rgb_to_hsv(np.clip(x, 0, 1))
        # OpenCV float HSV hue is in degrees [0, 360); 0.2 rad ≈ 11.46 deg.
        delta_deg = np.degrees(rng.uniform(-0.2, 0.2))
        hsv[..., 0] = (hsv[..., 0] + delta_deg) % 360.0
        return _hsv_to_rgb(hsv)

    def contrast(x):
        factor = rng.uniform(0.5, 1.5)
        mean = x.mean(axis=(0, 1), keepdims=True)
        return (x - mean) * factor + mean

    ops = (
        [brightness, saturation, hue, contrast]
        if ordering % 2 == 0
        else [brightness, contrast, saturation, hue]
    )
    out = img.astype(np.float32)
    for op in ops:
        out = op(out)
    return np.clip(out, 0.0, 1.0)


def preprocess_imagenet_train(
    img: np.ndarray,
    rng: np.random.Generator,
    *,
    size: int = 224,
    bbox: Optional[np.ndarray] = None,
    color_ordering: Optional[int] = None,
) -> np.ndarray:
    """Full inception training preprocessing: distorted-bbox crop → resize
    → flip → color distort → scale to [-1, 1] (R9's transform list)."""
    top, left, h, w = sample_distorted_bounding_box(img.shape[:2], rng, bbox=bbox)
    crop = img[top : top + h, left : left + w]
    out = resize_bilinear(crop, size, size) / 255.0
    out = random_flip_left_right(out, rng)
    if color_ordering is None:
        color_ordering = int(rng.integers(0, 2))
    out = distort_color(out, rng, color_ordering)
    return (out - 0.5) * 2.0


def preprocess_imagenet_eval(
    img: np.ndarray, *, size: int = 224, crop_fraction: float = 0.875
) -> np.ndarray:
    """Eval path: central crop → resize → scale to [-1, 1]."""
    out = central_crop(img, crop_fraction)
    out = resize_bilinear(out, size, size) / 255.0
    return (out - 0.5) * 2.0


# --------------------------------------------------------------------------
# Batched on-device variants (JAX) for the cheap transforms.  Random crops
# use static output shapes (dynamic_slice with traced offsets) so they stay
# jittable — the XLA-friendly form of the same augmentations.
# --------------------------------------------------------------------------


def jax_per_image_standardization(images):
    import jax.numpy as jnp

    x = images.astype(jnp.float32)
    axes = tuple(range(1, x.ndim))
    n = np.prod(x.shape[1:])
    mean = x.mean(axis=axes, keepdims=True)
    std = jnp.maximum(x.std(axis=axes, keepdims=True), 1.0 / np.sqrt(n))
    return (x - mean) / std


def jax_random_flip(images, rng):
    import jax
    import jax.numpy as jnp

    flips = jax.random.bernoulli(rng, 0.5, (images.shape[0],))
    return jnp.where(
        flips[:, None, None, None], images[:, :, ::-1, :], images
    )


def jax_random_crop_with_pad(images, rng, pad: int = 4):
    import jax
    import jax.numpy as jnp

    n, h, w, c = images.shape
    padded = jnp.pad(images, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    tops = jax.random.randint(jax.random.fold_in(rng, 0), (n,), 0, 2 * pad + 1)
    lefts = jax.random.randint(jax.random.fold_in(rng, 1), (n,), 0, 2 * pad + 1)

    def crop_one(img, top, left):
        return jax.lax.dynamic_slice(img, (top, left, 0), (h, w, c))

    return jax.vmap(crop_one)(padded, tops, lefts)
