"""Known-bad elastic-resize protocol: the re-split pick decided per-host."""


def adopt_pick_chief_only(consensus, is_chief, local_pick):
    if is_chief:
        return consensus.broadcast_int(local_pick)
    return local_pick


def announce_positions(consensus, states):
    for pid in set(states):
        consensus.broadcast_int(pid)
    return len(states)
