"""Static analysis for the distributed_tensorflow_models_tpu repo.

``analysis.dtmlint`` is a dependency-free, AST-based invariant checker
encoding the contracts this codebase has already paid to learn at
runtime: collective lockstep (no one-host deadlocks), int32-only
collective wire values, jax-free supervisor modules, thread/signal
discipline, determinism of everything feeding checkpointed state, and
the metric-key registry.  ``scripts/dtm_lint.py`` is the CLI;
``tests/test_lint.py`` pins the package clean (modulo
``analysis/baseline.json``) in tier-1.

Stdlib-only by design — the checker itself lives inside the jax-free
zone it enforces.
"""

from analysis import dtmlint  # noqa: F401
