"""TFRecord container format: framing, CRC, shard iteration.

The reference reads ImageNet as TFRecord shards through TF's C++
``TFRecordReader`` kernel (SURVEY.md §2.1 R9; TF io_ops.py:542).  This module
reimplements the *container format* natively so the framework can ingest the
same files with zero TensorFlow dependency:

    record := length  : uint64 little-endian
              crc32c(length) masked : uint32 LE
              data    : bytes[length]
              crc32c(data) masked   : uint32 LE
    masked(c) = ((c >> 15) | (c << 17)) + 0xa282ead8   (mod 2^32)

A native C++ fast path (``native/tfrecord_loader.cc``, loaded via ctypes)
handles bulk reading; this pure-Python implementation is the always-available
fallback and the reference semantics for tests.
"""

from __future__ import annotations

import os
import struct
from typing import Iterable, Iterator, Sequence

_CRC_TABLE: list[int] | None = None
_MASK_DELTA = 0xA282EAD8


def _make_table() -> list[int]:
    # CRC-32C (Castagnoli), reflected, polynomial 0x1EDC6F41.
    poly = 0x82F63B78
    table = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        table.append(c)
    return table


def crc32c(data: bytes, value: int = 0) -> int:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        _CRC_TABLE = _make_table()
    crc = value ^ 0xFFFFFFFF
    table = _CRC_TABLE
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    crc = crc32c(data)
    return (((crc >> 15) | (crc << 17)) + _MASK_DELTA) & 0xFFFFFFFF


class CorruptRecordError(IOError):
    pass


def read_records(
    path: str | os.PathLike, *, verify_crc: bool = True
) -> Iterator[bytes]:
    """Yield raw record payloads from one TFRecord file."""
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                return
            if len(header) < 12:
                raise CorruptRecordError(f"{path}: truncated length header")
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:12])
            if verify_crc and masked_crc32c(header[:8]) != len_crc:
                raise CorruptRecordError(f"{path}: bad length CRC")
            data = f.read(length)
            if len(data) < length:
                raise CorruptRecordError(f"{path}: truncated record")
            footer = f.read(4)
            if len(footer) < 4:
                raise CorruptRecordError(f"{path}: truncated data CRC")
            if verify_crc:
                (data_crc,) = struct.unpack("<I", footer)
                if masked_crc32c(data) != data_crc:
                    raise CorruptRecordError(f"{path}: bad data CRC")
            yield data


def write_records(path: str | os.PathLike, records: Iterable[bytes]) -> int:
    """Write payloads as a TFRecord file; returns the record count.

    (The reference never writes records — its dataset-prep scripts do — but
    a writer is required for self-contained tests and synthetic shards.)
    """
    n = 0
    with open(path, "wb") as f:
        for data in records:
            header = struct.pack("<Q", len(data))
            f.write(header)
            f.write(struct.pack("<I", masked_crc32c(header)))
            f.write(data)
            f.write(struct.pack("<I", masked_crc32c(data)))
            n += 1
    return n


class ShardedRecordIterator:
    """Deterministic, checkpointable iterator over a set of TFRecord shards.

    Replaces ``string_input_producer`` + ``TFRecordReader`` (SURVEY.md §3.4
    lines 1-2): shard order is a seeded permutation per epoch, and the
    position (epoch, shard index, record index) is exposed as state so a
    restored run resumes mid-epoch — a capability the reference *lacks*
    (its queues restart from scratch on recovery; SURVEY.md §5.3-5.4).
    """

    def __init__(
        self,
        paths: Sequence[str],
        *,
        shuffle_shards: bool = True,
        seed: int = 0,
        native: bool | None = None,
        num_epochs: int | None = None,
    ):
        """``native``: None = use the C++ loader when built, True = require
        it (raise if missing), False = pure Python.  ``num_epochs``: stop
        after that many passes (eval loops need exactly one); None = loop
        forever (training)."""
        if not paths:
            raise ValueError("no shard paths given")
        self._paths = list(paths)
        self._shuffle = shuffle_shards
        self._seed = seed
        self._epoch = 0
        self._shard_idx = 0
        self._record_idx = 0
        self._native = native
        self._num_epochs = num_epochs

    def _epoch_order(self) -> list[str]:
        if not self._shuffle:
            return self._paths
        import numpy as np

        order = np.random.RandomState(
            (self._seed + self._epoch) & 0x7FFFFFFF
        ).permutation(len(self._paths))
        return [self._paths[i] for i in order]

    def _read_shard(self, path: str) -> Iterator[bytes]:
        use_native = self._native
        if use_native is None or use_native:
            from distributed_tensorflow_models_tpu.data import native_loader

            if native_loader.available():
                return iter(native_loader.read_all_records(path))
            if use_native:
                raise RuntimeError(
                    "native=True but the native library is not built; "
                    "run `make -C native` or pass native=None for "
                    "automatic fallback"
                )
        return read_records(path)

    def get_state(self) -> dict:
        return {
            "epoch": self._epoch,
            "shard_idx": self._shard_idx,
            "record_idx": self._record_idx,
        }

    def set_state(self, state: dict) -> None:
        self._epoch = int(state["epoch"])
        self._shard_idx = int(state["shard_idx"])
        self._record_idx = int(state["record_idx"])

    def __iter__(self) -> Iterator[bytes]:
        while self._num_epochs is None or self._epoch < self._num_epochs:
            order = self._epoch_order()
            while self._shard_idx < len(order):
                path = order[self._shard_idx]
                for i, rec in enumerate(self._read_shard(path)):
                    if i < self._record_idx:
                        continue
                    self._record_idx = i + 1
                    yield rec
                self._shard_idx += 1
                self._record_idx = 0
            self._epoch += 1
            self._shard_idx = 0
