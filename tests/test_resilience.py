"""Resilience tests: preemption grace, divergence rollback, restore
hardening, the chaos injector, the watchdog, and restart backoff.

The chaos acceptance contract (ISSUE 4): under injected pipeline-worker
failure, mid-run SIGTERM, and torn-checkpoint faults, training resumes
and the final ``TrainState`` is **bit-identical** to the fault-free run;
under injected NaN with ``nan_policy="rollback"`` the run completes with
exactly the offending chunk's batches skipped and the
``train/rollbacks``/``train/skipped_batches`` counters reflecting it;
with ``nan_policy="abort"`` (default) behavior is unchanged.

All runs are the tiny LeNet config on the fake 8-device CPU mesh; the
fault-free reference trajectory is computed once per module.
"""

import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_tensorflow_models_tpu import resilience, telemetry
from distributed_tensorflow_models_tpu.core import train_loop
from distributed_tensorflow_models_tpu.harness import (
    checkpoint as ckptlib,
    config as configlib,
    hooks as hooklib,
    train as trainlib,
)
from distributed_tensorflow_models_tpu.resilience import chaos as chaoslib
from distributed_tensorflow_models_tpu.resilience import fsck as fscklib

_SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


def _load_script(name):
    from importlib import util as importutil

    spec = importutil.spec_from_file_location(
        name, os.path.join(_SCRIPTS, f"{name}.py")
    )
    mod = importutil.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


STEPS = 8


def _cfg(**kw):
    base = dict(
        train_steps=STEPS,
        global_batch_size=32,
        log_every_steps=2,
        checkpoint_every_secs=10_000.0,
    )
    base.update(kw)
    return configlib.get_config("lenet_mnist", **base)


def _host_tree(tree):
    return jax.tree.map(np.asarray, tree)


def _assert_states_bit_identical(a, b):
    """Exact (bitwise) equality of params AND optimizer slots — the
    strongest statement that recovery replayed the same trajectory."""
    for name, ta, tb in (("params", a.params, b.params),
                         ("opt_state", a.opt_state, b.opt_state)):
        la = jax.tree_util.tree_leaves(ta)
        lb = jax.tree_util.tree_leaves(tb)
        assert len(la) == len(lb), name
        for x, y in zip(la, lb):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.fixture(scope="module")
def baseline(mesh8, tmp_path_factory):
    """The fault-free run every recovery test compares against.  Runs
    under the watchdog (which must not perturb the trajectory — the
    bit-identity tests double as proof)."""
    workdir = tmp_path_factory.mktemp("baseline")
    return trainlib.fit(
        _cfg(watchdog_timeout_s=300.0), str(workdir), mesh=mesh8
    )


# --------------------------------------------------------------------------
# Preemption grace
# --------------------------------------------------------------------------


def test_preemption_listener_flag_and_escalation():
    listener = resilience.PreemptionListener()
    assert listener.install()
    try:
        assert not listener.preempted
        signal.raise_signal(signal.SIGTERM)
        assert listener.preempted
        # SIGTERM again: still just the flag (idempotent grace).
        signal.raise_signal(signal.SIGTERM)
        assert listener.preempted
        # First ctrl-C — even after SIGTERM set the flag — stays
        # graceful: the operator's reflex must not kill the emergency
        # save mid-write.
        signal.raise_signal(signal.SIGINT)
        assert listener.preempted
        # Second ctrl-C escalates to KeyboardInterrupt.
        with pytest.raises(KeyboardInterrupt):
            signal.raise_signal(signal.SIGINT)
    finally:
        listener.uninstall()


def test_chaos_sigterm_preempts_then_resumes_bit_identical(
    mesh8, tmp_path, baseline
):
    """Mid-run SIGTERM → emergency checkpoint + preempted marker; the
    rerun resumes and finishes bit-identical to the fault-free run.  The
    first run goes through recoverable_fit, which must hand the
    preempted result back (resumable) instead of burning a restart."""
    cfg = _cfg(chaos={"sigterm_at_step": 4})
    first = trainlib.recoverable_fit(
        cfg, str(tmp_path), mesh=mesh8, backoff_base_s=0.0
    )
    assert first.preempted
    assert int(first.state.step) == 4  # stopped at the signal's boundary
    # The emergency checkpoint is durable and restorable.
    mgr = ckptlib.CheckpointManager(str(tmp_path))
    assert mgr.latest_step() == 4
    mgr.close()
    # Preemption is an abnormal exit: the flight recorder must hold the
    # incident (ISSUE 7) — graceful-path dump, schema-clean, with the
    # chaos fire and the preemption marker on the timeline.
    record = json.load(
        open(os.path.join(str(tmp_path), "flight_recorder_p0.json"))
    )
    assert record["reason"] == "preempted"
    assert record["step"] == 4
    assert _load_script("check_metrics_schema").check_flight_record(
        record
    ) == []
    names = [e["name"] for e in record["events"]]
    assert "chaos/sigterm_at_step" in names
    assert "train/preempted" in names

    second = trainlib.fit(cfg, str(tmp_path), mesh=mesh8)
    assert not second.preempted
    assert second.steps_run == STEPS - 4  # resumed, not re-trained
    assert int(second.state.step) == STEPS
    _assert_states_bit_identical(second.state, baseline.state)


# --------------------------------------------------------------------------
# Pipeline-worker fault
# --------------------------------------------------------------------------


def test_pipeline_worker_fault_recovers_bit_identical(
    mesh8, tmp_path, baseline
):
    """assemble() raises inside the producer at batch 3: the crash-time
    save holds the exact consumed position, the restart replays the
    failed batch (chaos fires once per process), and the final state is
    bit-identical to fault-free.  Run with a worker pool so the fault
    travels the ordered-reassembly path."""
    cfg = _cfg(chaos={"pipeline_fail_at_batch": 3}, data_workers=2)
    res = trainlib.recoverable_fit(
        cfg, str(tmp_path), mesh=mesh8, max_restarts=2, backoff_base_s=0.0
    )
    assert int(res.state.step) == STEPS
    _assert_states_bit_identical(res.state, baseline.state)
    with open(os.path.join(str(tmp_path), "telemetry.json")) as f:
        snap = json.load(f)["metrics"]
    assert snap.get("train/restarts") == 1.0


# --------------------------------------------------------------------------
# Torn checkpoint → restore hardening walk-back
# --------------------------------------------------------------------------


def test_torn_checkpoint_walks_back_and_resumes_bit_identical(
    mesh8, tmp_path, baseline
):
    """The only checkpoint is torn after finalization: fsck reports it,
    restore_or_init falls back to a fresh init (better than a dead job),
    and the re-trained run is bit-identical to fault-free."""
    cfg4 = _cfg(train_steps=4, chaos={"torn_checkpoint_at_step": 4})
    trainlib.fit(cfg4, str(tmp_path), mesh=mesh8)

    ckpt_dir = os.path.join(str(tmp_path), "checkpoints")
    report = fscklib.fsck_checkpoints(ckpt_dir)
    assert report["latest_step"] == 4
    assert report["steps"][-1]["valid"] is False
    assert report["newest_valid_step"] is None

    cfg8 = _cfg(chaos={"torn_checkpoint_at_step": 4})
    res = trainlib.fit(cfg8, str(tmp_path), mesh=mesh8)
    assert res.steps_run == STEPS  # fresh re-train: nothing restorable
    _assert_states_bit_identical(res.state, baseline.state)


def test_mid_run_tear_fires_without_save_cadence(mesh8, tmp_path, baseline):
    """``torn_checkpoint_at_step`` must fire even when no save cadence
    lands at that step (the clock cadence here is effectively off): the
    injector's tear hook forces a durable save at k and tears it, the
    run completes unperturbed, and fsck reports the torn step next to
    the valid final checkpoint."""
    cfg = _cfg(chaos={"torn_checkpoint_at_step": 3})
    res = trainlib.fit(cfg, str(tmp_path), mesh=mesh8)
    assert res.steps_run == STEPS
    _assert_states_bit_identical(res.state, baseline.state)
    report = fscklib.fsck_checkpoints(
        os.path.join(str(tmp_path), "checkpoints")
    )
    by_step = {s["step"]: s["valid"] for s in report["steps"]}
    assert by_step[3] is False  # the tear really injected
    assert report["newest_valid_step"] == STEPS  # final save intact


def test_sigterm_resume_bit_identical_at_tight_cadence(
    mesh8, tmp_path, baseline
):
    """ISSUE 6: overlapped (dispatch-only) saves let checkpoint_every_steps
    tighten — here 2, under the fused loop — and the kill/resume contract
    must stay bit-identical: SIGTERM at step 4 (emergency save fenced
    explicitly), rerun resumes from the step-4 save and finishes equal to
    the fault-free run."""
    cfg = _cfg(
        chaos={"sigterm_at_step": 4},
        checkpoint_every_steps=2,
        steps_per_loop=2,
    )
    first = trainlib.recoverable_fit(
        cfg, str(tmp_path), mesh=mesh8, backoff_base_s=0.0
    )
    assert first.preempted
    assert int(first.state.step) == 4
    second = trainlib.fit(cfg, str(tmp_path), mesh=mesh8)
    assert second.steps_run == STEPS - 4
    _assert_states_bit_identical(second.state, baseline.state)


def test_torn_newest_walks_back_bit_identical_at_tight_cadence(
    mesh8, tmp_path, baseline
):
    """Tightened cadence (2) + the newest checkpoint (the step-5 end
    save) torn after finalization: resume walks back to the step-4
    cadence save — NOT a fresh init, which is exactly the replay-length
    win the tight cadence buys — and the replayed run is bit-identical
    to fault-free.  (A torn step that also has a later save at the same
    step is self-healed by the save path's torn-dir replacement, so the
    tear targets the run's final save.)"""
    cfg5 = _cfg(
        train_steps=5,
        checkpoint_every_steps=2,
        chaos={"torn_checkpoint_at_step": 5},
    )
    trainlib.fit(cfg5, str(tmp_path), mesh=mesh8)
    report = fscklib.fsck_checkpoints(
        os.path.join(str(tmp_path), "checkpoints")
    )
    assert report["latest_step"] == 5
    assert report["newest_valid_step"] == 4  # the end save really torn

    cfg8 = _cfg(
        checkpoint_every_steps=2, chaos={"torn_checkpoint_at_step": 5}
    )
    res = trainlib.fit(cfg8, str(tmp_path), mesh=mesh8)
    assert res.steps_run == STEPS - 4  # resumed at 4, replayed 5..8
    _assert_states_bit_identical(res.state, baseline.state)


def test_chaos_warns_when_fault_never_fires(mesh8, tmp_path, caplog):
    """A drill whose fault position is never reached must say so — an
    exit-0 run with a silently unfired fault would read as a passed
    drill that never exercised anything."""
    import logging

    cfg = _cfg(chaos={"nan_at_step": 10_000})
    with caplog.at_level(logging.WARNING, logger="dtm"):
        res = trainlib.fit(cfg, str(tmp_path), mesh=mesh8)
    assert res.steps_run == STEPS
    assert "never fired" in caplog.text
    assert "nan_at_step=10000" in caplog.text


def _tiny_state(step=0):
    from distributed_tensorflow_models_tpu.core.train_state import TrainState
    from distributed_tensorflow_models_tpu.models import get_model
    from distributed_tensorflow_models_tpu.ops import optim

    state = TrainState.create(
        get_model("lenet", num_classes=4),
        optim.tf_momentum(0.1, 0.9),
        jax.random.key(0),
        jnp.zeros((2, 28, 28, 1)),
    )
    return state.replace(step=jnp.asarray(step, jnp.int32))


def _tear(ckpt_dir, step, names=("_METADATA", "manifest.ocdbt")):
    for name in names:
        path = os.path.join(ckpt_dir, str(step), "state", name)
        if os.path.exists(path):
            os.remove(path)


def test_restore_walks_back_to_newest_valid_step(tmp_path):
    """Synthesized torn latest: restore(step=None) silently returns the
    previous valid step; the explicit-step path still errors."""
    mgr = ckptlib.CheckpointManager(str(tmp_path), keep=5)
    for step in (1, 2, 3):
        assert mgr.save(_tiny_state(step), {"pos": step}, force=True)
    mgr.wait()
    _tear(mgr.directory, 3)

    restored, data = mgr.restore(_tiny_state())
    assert int(restored.step) == 2
    assert data == {"pos": 2}
    with pytest.raises(Exception):
        mgr.restore(_tiny_state(), step=3)  # explicit step: no walk-back
    mgr.close()


def test_train_resume_walks_past_non_finite_crash_save(tmp_path):
    """A structurally-valid checkpoint holding post-divergence NaN state
    (e.g. CheckpointHook.abort's crash-save after a NaN trip) must not
    brick the workdir: the TRAINING resume path (restore_or_init) gates
    on finiteness and restores the newest FINITE step, while the plain
    restore() eval/generate use stays ungated and sees the newest
    structurally-valid step."""
    mgr = ckptlib.CheckpointManager(str(tmp_path), keep=5)
    assert mgr.save(_tiny_state(1), {"pos": 1}, force=True)
    poisoned = _tiny_state(2)
    poisoned = poisoned.replace(
        params=jax.tree.map(lambda x: x * jnp.nan, poisoned.params)
    )
    assert mgr.save(poisoned, {"pos": 2}, force=True)
    mgr.wait()
    state, data, restored = ckptlib.restore_or_init(mgr, _tiny_state())
    assert restored and int(state.step) == 1
    assert data == {"pos": 1}
    # Eval-style restore is ungated: newest structurally-valid step wins.
    evaled, _ = mgr.restore(_tiny_state())
    assert int(evaled.step) == 2
    mgr.close()


def test_restore_or_init_fresh_when_everything_torn(tmp_path):
    mgr = ckptlib.CheckpointManager(str(tmp_path), keep=5)
    assert mgr.save(_tiny_state(1), {"pos": 1}, force=True)
    mgr.wait()
    _tear(mgr.directory, 1)
    template = _tiny_state()
    state, data, restored = ckptlib.restore_or_init(mgr, template)
    assert not restored and state is template and data == {}
    mgr.close()


def test_corrupt_dataset_sidecar_falls_back_to_primary(tmp_path, caplog):
    """Satellite bugfix: a truncated sidecar must degrade to the
    primary's position (like a missing one), not kill the restore."""
    mgr = ckptlib.CheckpointManager(
        str(tmp_path), keep=2, process_index=1, process_count=2
    )
    assert mgr.save(_tiny_state(5), {"pos": "primary"})
    mgr.wait()
    sidecar = os.path.join(
        str(tmp_path), "checkpoints/dataset_states/5/p1.json"
    )
    with open(sidecar, "w") as f:
        f.write('{"nproc": 2, "state": {"pos": "sid')  # torn write
    import logging

    with caplog.at_level(logging.WARNING, logger="dtm"):
        _, data = mgr.restore(_tiny_state())
    assert data == {"pos": "primary"}
    assert "unreadable" in caplog.text
    mgr.close()


def test_fsck_script_reports_and_repairs(tmp_path, capsys):
    """scripts/fsck_checkpoints.py: torn latest + stale-topology sidecar
    + unparseable sidecar are all reported; --repair removes the torn
    step so the next restore target is the newest valid step."""
    fsck_checkpoints = _load_script("fsck_checkpoints")

    mgr = ckptlib.CheckpointManager(
        str(tmp_path), keep=5, process_index=0, process_count=2
    )
    for step in (1, 2):
        assert mgr.save(_tiny_state(step), {"pos": step}, force=True)
    mgr.wait()
    # Stale topology stamp on step 1's sidecar; garbage on step 2's.
    with open(
        os.path.join(mgr.directory, "dataset_states/1/p0.json"), "w"
    ) as f:
        json.dump({"nproc": 4, "state": {}}, f)
    with open(
        os.path.join(mgr.directory, "dataset_states/2/p0.json"), "w"
    ) as f:
        f.write("not json")
    _tear(mgr.directory, 2)
    mgr.close()

    rc = fsck_checkpoints.main([str(tmp_path), "--process-count", "2"])
    out = capsys.readouterr().out
    assert rc == 1  # latest is torn: restore would walk back
    assert "TORN" in out and "WALK BACK" in out
    assert "topology stamp nproc=4" in out
    assert "unreadable" in out

    rc = fsck_checkpoints.main([str(tmp_path), "--repair"])
    out = capsys.readouterr().out
    assert rc == 0  # torn step removed; newest valid (1) is now latest
    assert "repaired" in out
    report = fscklib.fsck_checkpoints(os.path.join(str(tmp_path), "checkpoints"))
    assert report["latest_step"] == 1
    assert report["newest_valid_step"] == 1


# --------------------------------------------------------------------------
# Divergence rollback
# --------------------------------------------------------------------------


def test_nan_abort_default_unchanged(mesh8, tmp_path):
    """nan_policy="abort" (default): the injected NaN propagates exactly
    as the reference NanTensorHook would — no rollback machinery."""
    cfg = _cfg(train_steps=4, chaos={"nan_at_step": 2})
    with pytest.raises(FloatingPointError, match="at step 2"):
        trainlib.fit(cfg, str(tmp_path), mesh=mesh8)


def test_nan_rollback_skips_exactly_one_batch_unfused(mesh8, tmp_path):
    """Unfused loop: the offending "chunk" is one step — exactly one
    batch is skipped, once, and the run completes with finite loss."""
    cfg = _cfg(nan_policy="rollback", chaos={"nan_at_step": 4})
    res = trainlib.fit(cfg, str(tmp_path), mesh=mesh8)
    assert int(res.state.step) == STEPS
    assert res.rollbacks == 1
    assert res.skipped_batches == 1
    assert np.isfinite(res.final_metrics["loss"])
    with open(os.path.join(str(tmp_path), "telemetry.json")) as f:
        snap = json.load(f)["metrics"]
    assert snap["train/rollbacks"] == 1.0
    assert snap["train/skipped_batches"] == 1.0
    # The injected counters ride metrics.jsonl rows (schema-linted set).
    rows = [
        json.loads(line)
        for line in open(os.path.join(str(tmp_path), "metrics.jsonl"))
    ]
    assert rows[-1]["rollbacks"] == 1.0 and rows[-1]["skipped_batches"] == 1.0
    # A rollback is an abnormal event even though the run survives: the
    # flight recorder holds the divergence → restore → skip sequence
    # (ISSUE 7), schema-clean, with the restored step in the marker.
    record = json.load(
        open(os.path.join(str(tmp_path), "flight_recorder_p0.json"))
    )
    assert record["reason"] == "rollback"
    assert _load_script("check_metrics_schema").check_flight_record(
        record
    ) == []
    by_name = {}
    for e in record["events"]:
        by_name.setdefault(e["name"], e)
    assert "chaos/nan_at_step" in by_name
    assert "train/divergence" in by_name
    assert by_name["train/rollback"]["args"]["offender_start"] == 3
    assert "train/skip_batches" in by_name


def test_nan_rollback_skips_exactly_offending_chunk_fused(mesh8, tmp_path):
    """Fused loop (steps_per_loop=4): a mid-chunk NaN rolls back and
    skips exactly that chunk's 4 batches — the exactly-K-skipped
    acceptance contract."""
    cfg = _cfg(
        nan_policy="rollback",
        steps_per_loop=4,
        log_every_steps=4,
        chaos={"nan_at_step": 3},
    )
    res = trainlib.fit(cfg, str(tmp_path), mesh=mesh8)
    assert int(res.state.step) == STEPS
    assert res.rollbacks == 1
    assert res.skipped_batches == 4
    assert np.isfinite(res.final_metrics["loss"])


def test_nan_rollback_detects_off_cadence_divergence(mesh8, tmp_path):
    """Rollback guards EVERY chunk itself (one readback per chunk), so
    detection lands in the offending chunk even when the NaN guard's
    log-cadence walk would have missed it entirely — here the cadence
    (100) never fires within the run at all."""
    cfg = _cfg(
        nan_policy="rollback",
        steps_per_loop=4,
        log_every_steps=100,
        chaos={"nan_at_step": 6},
    )
    res = trainlib.fit(cfg, str(tmp_path), mesh=mesh8)
    assert int(res.state.step) == STEPS
    assert res.rollbacks == 1
    assert res.skipped_batches == 4  # exactly the offending chunk (5..8)
    assert train_loop.state_is_finite(res.state)


def test_nan_rollback_budget_exhausts_on_persistent_divergence(
    mesh8, tmp_path
):
    """A divergence that survives rollback (here: a hook that raises at
    every attempt) must exhaust the budget and abort — never loop."""

    class AlwaysNan(hooklib.Hook):
        def after_step(self, state, metrics, step):
            if step == 2:
                raise FloatingPointError("loss is nan at step 2")

    cfg = _cfg(train_steps=4, nan_policy="rollback", rollback_budget=1)
    with pytest.raises(FloatingPointError):
        trainlib.fit(
            cfg, str(tmp_path), mesh=mesh8, extra_hooks=[AlwaysNan()]
        )


def test_save_at_existing_step_is_idempotent_not_fatal(tmp_path):
    """Orbax raises StepAlreadyExistsError on a re-save (force=True
    included); the manager must treat it as already-durable instead —
    the preemption emergency save can land at a boundary the cadence
    save just wrote, and a crash there turns grace into failure."""
    mgr = ckptlib.CheckpointManager(str(tmp_path), keep=5)
    assert mgr.save(_tiny_state(3), {"pos": 3}, force=True)
    mgr.wait()
    assert mgr.save(_tiny_state(3), {"pos": 3}, force=True) is False
    assert mgr.all_steps() == [3]
    mgr.close()


def test_save_replaces_torn_dir_at_same_step(tmp_path):
    """The idempotency skip must not trust a torn dir: a real save at
    that step (e.g. the emergency save after the cadence save's write
    was damaged) replaces the damage instead of silently no-opping."""
    mgr = ckptlib.CheckpointManager(str(tmp_path), keep=5)
    assert mgr.save(_tiny_state(3), {"pos": "old"}, force=True)
    mgr.wait()
    _tear(mgr.directory, 3)
    assert mgr.save(_tiny_state(3), {"pos": "new"}, force=True)
    mgr.wait()
    restored, data = mgr.restore(_tiny_state())
    assert int(restored.step) == 3 and data == {"pos": "new"}
    mgr.close()


def test_rollback_anchor_exists_after_torn_fresh_init(mesh8, tmp_path):
    """Fresh-init fallback (checkpoints exist but all torn) must still
    bank the rollback anchor — gated on `not restored`, not on
    latest_step() — so the first divergence has a rewind target."""
    cfg4 = _cfg(train_steps=4, chaos={"torn_checkpoint_at_step": 4})
    trainlib.fit(cfg4, str(tmp_path), mesh=mesh8)  # leaves only torn 4
    cfg8 = _cfg(nan_policy="rollback", chaos={"nan_at_step": 6})
    res = trainlib.fit(cfg8, str(tmp_path), mesh=mesh8)
    assert int(res.state.step) == STEPS
    assert res.rollbacks == 1 and res.skipped_batches == 1
    assert train_loop.state_is_finite(res.state)


def test_rollback_deletes_post_divergence_checkpoints(tmp_path):
    """CheckpointManager.delete removes a retained step (what _rollback
    uses to clear the abandoned timeline so replay saves aren't shadowed
    by stale post-divergence checkpoints)."""
    mgr = ckptlib.CheckpointManager(str(tmp_path), keep=5)
    for s in (1, 2):
        assert mgr.save(_tiny_state(s), {"pos": s}, force=True)
    mgr.wait()
    mgr.delete(2)
    assert mgr.all_steps() == [1]
    # The freed step can be saved again (the replay's own save).
    assert mgr.save(_tiny_state(2), {"pos": "replay"}, force=True)
    mgr.close()


def test_launch_aggregate_exit_codes():
    from distributed_tensorflow_models_tpu import launch

    R = launch.RESUMABLE_EXIT_CODE
    assert launch.aggregate_exit_codes([0, 0]) == 0
    assert launch.aggregate_exit_codes([0, R]) == R
    # A real failure must win over "preempted" — never relabeled resumable.
    assert launch.aggregate_exit_codes([R, 1]) == 1
    assert launch.aggregate_exit_codes([2, R, 0]) == 2
    assert launch.aggregate_exit_codes([]) == 0


def test_state_is_finite():
    state = _tiny_state()
    assert train_loop.state_is_finite(state)
    bad = state.replace(
        params=jax.tree.map(lambda x: x * jnp.nan, state.params)
    )
    assert not train_loop.state_is_finite(bad)


# --------------------------------------------------------------------------
# Watchdog
# --------------------------------------------------------------------------


def test_watchdog_diagnoses_stall_and_escalates(caplog):
    import logging
    import time

    reg = telemetry.MetricsRegistry()
    fired = []
    wd = resilience.ProgressWatchdog(
        0.05,
        registry=reg,
        abort=True,
        abort_fn=lambda: fired.append(1),
        poll_s=0.01,
    )
    try:
        with caplog.at_level(logging.ERROR, logger="dtm"):
            # Abort is disarmed until the first completed chunk (the
            # initial-compile grace): a never-beaten watchdog warns only.
            time.sleep(0.25)
            assert not fired
            assert "no training progress" in caplog.text
            wd.beat(1)  # first chunk done: abort arms
            deadline = time.time() + 5.0
            while not fired and time.time() < deadline:
                time.sleep(0.01)
    finally:
        wd.stop()
    assert fired  # abort_fn ran (from the second timeout interval on)
    assert "no training progress" in caplog.text
    assert reg.snapshot()[telemetry.WATCHDOG_LAST_PROGRESS] > 0.0
    # A beat resets the stall clock and the gauge.
    wd2 = resilience.ProgressWatchdog(10.0, registry=reg, poll_s=0.01)
    wd2.beat(7)
    wd2.stop()
    assert reg.snapshot()[telemetry.WATCHDOG_LAST_PROGRESS] == 0.0


def test_fit_setup_failure_releases_signal_handlers(mesh8, tmp_path):
    """A failure between handler install and the main loop (here: an
    invalid watchdog timeout) must not leak the replaced SIGTERM/SIGINT
    handlers, the watchdog thread, or the already-started input-pipeline
    threads into the caller."""
    import threading

    before = (
        signal.getsignal(signal.SIGTERM), signal.getsignal(signal.SIGINT)
    )
    cfg = _cfg(train_steps=2, watchdog_timeout_s=-5.0)
    with pytest.raises(ValueError, match="watchdog timeout"):
        trainlib.fit(cfg, str(tmp_path), mesh=mesh8)
    after = (
        signal.getsignal(signal.SIGTERM), signal.getsignal(signal.SIGINT)
    )
    assert after == before
    leaked = [
        t.name
        for t in threading.enumerate()
        if t.is_alive()
        and t.name.startswith(("host-pipeline", "data-worker"))
    ]
    assert leaked == []


def test_fit_hook_setup_failure_leaks_nothing(mesh8, tmp_path):
    """A failure AFTER the pipeline threads start but before the main
    loop (here: MetricWriterHook's eager open hitting a metrics path
    occupied by a directory) must tear down the pipeline and restore
    the signal handlers, same as the watchdog-validation failure."""
    import threading

    before = (
        signal.getsignal(signal.SIGTERM), signal.getsignal(signal.SIGINT)
    )
    (tmp_path / "metrics.jsonl").mkdir()
    with pytest.raises(OSError):
        trainlib.fit(_cfg(train_steps=2), str(tmp_path), mesh=mesh8)
    assert (
        signal.getsignal(signal.SIGTERM), signal.getsignal(signal.SIGINT)
    ) == before
    leaked = [
        t.name
        for t in threading.enumerate()
        if t.is_alive()
        and t.name.startswith(("host-pipeline", "data-worker"))
    ]
    assert leaked == []


def test_watchdog_abort_disabled_off_main_thread(caplog):
    """The default abort (interrupt_main) targets the main thread; a
    watchdog built off it must drop the abort (keeping the diagnosis)
    instead of interrupting the caller's unrelated work."""
    import logging
    import threading

    out = {}

    def build():
        with caplog.at_level(logging.WARNING, logger="dtm"):
            wd = resilience.ProgressWatchdog(10.0, abort=True, poll_s=0.01)
            out["abort"] = wd._abort
            wd.stop()

    t = threading.Thread(target=build)
    t.start()
    t.join()
    assert out["abort"] is False
    assert "watchdog abort disabled" in caplog.text
    # On the main thread the abort stays armed.
    wd = resilience.ProgressWatchdog(10.0, abort=True, poll_s=0.01)
    try:
        assert wd._abort is True
    finally:
        wd.stop()


def test_fit_with_watchdog_runs_clean(baseline):
    """Wiring smoke: the baseline run executed under the watchdog
    (fixture cfg) — a healthy run completes and leaks no watchdog
    thread."""
    import threading

    assert baseline.steps_run == STEPS
    assert not any(
        t.name == "progress-watchdog" for t in threading.enumerate()
    )


# --------------------------------------------------------------------------
# Restart backoff
# --------------------------------------------------------------------------


def test_restart_backoff_deterministic_jittered_growth():
    d1 = trainlib.restart_backoff(1, base_s=1.0, max_s=60.0, seed=3)
    d2 = trainlib.restart_backoff(2, base_s=1.0, max_s=60.0, seed=3)
    d5 = trainlib.restart_backoff(5, base_s=1.0, max_s=60.0, seed=3)
    assert d1 == trainlib.restart_backoff(1, base_s=1.0, max_s=60.0, seed=3)
    assert 0.5 <= d1 < 1.0  # half-jitter band of 1s
    assert 1.0 <= d2 < 2.0
    assert 8.0 <= d5 < 16.0
    # Jitter decorrelates seeds; the cap bounds the wait; 0 disables.
    assert d1 != trainlib.restart_backoff(1, base_s=1.0, max_s=60.0, seed=4)
    assert trainlib.restart_backoff(30, base_s=1.0, max_s=60.0, seed=3) <= 60.0
    assert trainlib.restart_backoff(3, base_s=0.0, seed=3) == 0.0


def test_recoverable_fit_sleeps_backoff(mesh8, tmp_path, monkeypatch):
    """The backoff waits on the PREEMPTION-AWARE listener.wait (not
    time.sleep — a notice must wake it immediately) for exactly the
    deterministic restart_backoff delay."""
    slept = []
    monkeypatch.setattr(
        resilience.PreemptionListener,
        "wait",
        lambda self, t: (slept.append(t), False)[1],
    )

    class Preempted(ConnectionError):
        pass

    cfg = _cfg(train_steps=2)
    fault = hooklib.FaultInjectionHook(1, lambda: Preempted("chip lost"))
    res = trainlib.recoverable_fit(
        cfg, str(tmp_path), mesh=mesh8, max_restarts=2,
        backoff_base_s=0.25, extra_hooks=[fault],
    )
    assert int(res.state.step) == 2
    assert slept == [
        trainlib.restart_backoff(1, base_s=0.25, max_s=60.0, seed=cfg.seed)
    ]


# --------------------------------------------------------------------------
# Chaos plumbing + schema lint
# --------------------------------------------------------------------------


def test_parse_chaos_spec():
    assert chaoslib.parse_chaos_spec("nan_at_step=5, sigterm_at_step=9") == {
        "nan_at_step": 5,
        "sigterm_at_step": 9,
    }
    assert chaoslib.parse_chaos_spec("") == {}
    with pytest.raises(ValueError, match="unknown chaos key"):
        chaoslib.parse_chaos_spec("explode_at=3")
    with pytest.raises(ValueError, match="key=value"):
        chaoslib.parse_chaos_spec("nan_at_step")
    with pytest.raises(ValueError, match="must be int"):
        chaoslib.parse_chaos_spec("nan_at_step=soon")


def test_cli_preempt_poll_steps_override():
    from types import SimpleNamespace

    from distributed_tensorflow_models_tpu.harness import cli

    args = SimpleNamespace(
        train_steps=None, batch_size=None, seed=None, preempt_poll_steps=7
    )
    assert cli._overrides(args)["preempt_poll_steps"] == 7


def test_chaos_injector_memoized_per_scope_and_fires_once():
    spec = {"pipeline_fail_at_batch": 1}
    a = chaoslib.get_injector(spec, seed=0, scope="/tmp/scope-a-xyz")
    b = chaoslib.get_injector(spec, seed=0, scope="/tmp/scope-a-xyz")
    c = chaoslib.get_injector(spec, seed=0, scope="/tmp/scope-b-xyz")
    assert a is b and a is not c
    assert chaoslib.get_injector({}, seed=0, scope="x") is None

    class TwoBatch:
        def __init__(self):
            self.i = 0

        def next_work(self):
            self.i += 1
            return self.i - 1

        def assemble(self, work):
            return {"x": np.zeros(1)}

    ds = a.wrap_dataset(TwoBatch())
    assert ds.assemble(ds.next_work()) is not None  # batch 0 fine
    with pytest.raises(chaoslib.ChaosPipelineError):
        ds.assemble(ds.next_work())  # batch 1 faults...
    assert ds.assemble(ds.next_work()) is not None  # ...exactly once


def test_chaos_pipeline_fault_warns_on_mid_process_reposition(caplog):
    """An armed pipeline fault counts dispatches, not stream batches —
    a mid-process cursor rewind (rollback replay) shifts its position,
    and that must be said out loud, not silently misfire."""
    import logging

    class DS:
        def __init__(self):
            self.i = 0

        def next_work(self):
            self.i += 1
            return self.i - 1

        def assemble(self, work):
            return {"x": np.zeros(1)}

        def get_state(self):
            return {"i": self.i}

        def set_state(self, s):
            self.i = s["i"]

    inj = chaoslib.ChaosInjector(
        chaoslib.ChaosConfig(pipeline_fail_at_batch=5)
    )
    ds = inj.wrap_dataset(DS())
    with caplog.at_level(logging.WARNING, logger="dtm"):
        ds.set_state({"i": 0})  # no dispatches yet: entry restore, silent
        assert "still armed" not in caplog.text
        ds.assemble(ds.next_work())
        ds.set_state({"i": 0})  # mid-process rewind: warn
    assert "still armed" in caplog.text


def test_metrics_schema_resilience_keys():
    check_lines = _load_script("check_metrics_schema").check_lines

    good = json.dumps(
        {
            "step": 1, "time": 1.0,
            "restarts": 0, "rollbacks": 1, "skipped_batches": 4,
        }
    )
    errors, rows, _ = check_lines([good])
    assert errors == [] and rows == 1
    errors, _, _ = check_lines(
        [json.dumps({"step": 1, "time": 1.0, "rollbacks": 1})]
    )
    assert any("partial resilience key set" in e for e in errors)
    errors, _, _ = check_lines(
        [
            json.dumps(
                {
                    "step": 1, "time": 1.0,
                    "restarts": -1, "rollbacks": 0, "skipped_batches": 0,
                }
            )
        ]
    )
    assert any("negative" in e for e in errors)
