"""Tests for the parallel package: tensor-parallel rules and async-PS
emulation (SURVEY.md §2.4, §7.6), on the 8-fake-CPU-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from distributed_tensorflow_models_tpu.core import mesh as meshlib
from distributed_tensorflow_models_tpu.core import sharding as shardlib
from distributed_tensorflow_models_tpu.core import train_loop
from distributed_tensorflow_models_tpu.core.mesh import AxisNames
from distributed_tensorflow_models_tpu.core.train_state import TrainState
from distributed_tensorflow_models_tpu.models import get_model
from distributed_tensorflow_models_tpu.ops import optim
from distributed_tensorflow_models_tpu.parallel import async_ps, tensor


def _lenet_state(tx=None, seed=0):
    model = get_model("lenet")
    tx = tx or optim.sgd(0.1)
    state = TrainState.create(
        model, tx, jax.random.key(seed), jnp.zeros((2, 28, 28, 1))
    )
    return model, state


def _batch(n=8, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "image": rng.rand(n, 28, 28, 1).astype(np.float32),
        "label": rng.randint(0, 10, (n,)),
    }


# ---------------------------------------------------------------- tensor TP


def test_cnn_tp_rules_assign_model_axis():
    mesh = meshlib.create_mesh(meshlib.MeshSpec(data=-1, model=2))
    _, state = _lenet_state()
    sh = shardlib.tree_param_shardings(
        mesh, state.params, tensor.cnn_tp_rules()
    )
    flat = {
        shardlib._path_str(p): s
        for p, s in jax.tree_util.tree_leaves_with_path(sh)
    }
    conv_kernels = [k for k in flat if "Conv" in k and k.endswith("kernel")]
    assert conv_kernels
    for k in conv_kernels:
        assert flat[k].spec == P(None, None, None, AxisNames.MODEL), k
    assert flat["head/kernel"].spec == P(None, AxisNames.MODEL)
    # Non-matching params (Dense_0) stay replicated.
    dense = [k for k in flat if k.startswith("Dense")]
    assert dense and all(flat[k].spec == P() for k in dense)


def test_tp_step_matches_data_parallel():
    """One train step with conv+head weights sharded over model=2 must
    match the pure-DP step numerically — TP changes layout, not math."""
    mesh_tp = meshlib.create_mesh(meshlib.MeshSpec(data=-1, model=2))
    mesh_dp = meshlib.data_parallel_mesh()
    model, state = _lenet_state()
    step = train_loop.make_train_step(
        train_loop.classification_loss_fn(model.apply)
    )
    batch = _batch()
    rng = jax.random.key(1)

    s_dp = train_loop.place_state(state, mesh_dp)
    s_dp, m_dp = step(s_dp, shardlib.shard_batch(mesh_dp, batch), rng)

    s_tp = train_loop.place_state(state, mesh_tp, tensor.cnn_tp_rules())
    s_tp, m_tp = step(s_tp, shardlib.shard_batch(mesh_tp, batch), rng)

    np.testing.assert_allclose(
        float(m_dp["loss"]), float(m_tp["loss"]), rtol=1e-5
    )
    a = jax.tree.leaves(s_dp.params)
    b = jax.tree.leaves(s_tp.params)
    for x, y in zip(a, b):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=1e-4, atol=1e-5
        )


def test_transformer_rules_shapes():
    rules = tensor.transformer_tp_rules()
    patterns = [p for p, _ in rules]
    assert any("query" in p for p in patterns)
    assert any("down" in p for p in patterns)


# ---------------------------------------------------------------- async PS


def _emulator(num_workers, schedule="round_robin", seed=0, limit=None):
    model, state = _lenet_state()
    loss_fn = train_loop.classification_loss_fn(model.apply)
    cfg = async_ps.AsyncConfig(
        num_workers=num_workers,
        schedule=schedule,
        seed=seed,
        staleness_limit=limit,
    )
    return model, async_ps.AsyncPSEmulator(state, loss_fn, cfg)


def test_async_one_worker_matches_sync():
    """K=1 async == the sync train step trajectory, bit-for-bit-ish."""
    model, emu = _emulator(1)
    _, state = _lenet_state()
    step = train_loop.make_train_step(
        train_loop.classification_loss_fn(model.apply), donate=False
    )
    rng = jax.random.key(7)
    batches = [_batch(seed=i) for i in range(3)]
    for b in batches:
        emu.step(b, rng)
        state, _ = step(state, b, rng)
    assert emu.staleness_log == [0, 0, 0]
    for x, y in zip(
        jax.tree.leaves(emu.state.params), jax.tree.leaves(state.params)
    ):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-7
        )


def test_async_round_robin_staleness():
    _, emu = _emulator(4)
    rng = jax.random.key(0)
    for i in range(8):
        emu.step(_batch(seed=i), rng)
    # Ramp-up 0,1,2,3 then steady-state K-1.
    assert emu.staleness_log == [0, 1, 2, 3, 3, 3, 3, 3]
    assert emu.dropped == 0


def test_async_staleness_limit_drops():
    _, emu = _emulator(4, limit=2)
    rng = jax.random.key(0)
    records = [emu.step(_batch(seed=i), rng) for i in range(8)]
    assert emu.dropped > 0
    assert any(r["dropped"] for r in records)
    # Dropped events must not advance the canonical step.
    applied = sum(1 for r in records if not r["dropped"])
    assert int(emu.state.step) == applied


def test_async_random_schedule_deterministic():
    _, emu1 = _emulator(4, schedule="random", seed=3)
    _, emu2 = _emulator(4, schedule="random", seed=3)
    rng = jax.random.key(0)
    r1 = [emu1.step(_batch(seed=i), rng)["worker"] for i in range(6)]
    r2 = [emu2.step(_batch(seed=i), rng)["worker"] for i in range(6)]
    assert r1 == r2
    for x, y in zip(
        jax.tree.leaves(emu1.state.params), jax.tree.leaves(emu2.state.params)
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_async_threads_ema_and_version():
    """EMA shadows advance through the emulator's apply, and workers built
    from a restored (step>0) state start at staleness 0, not step."""
    model = get_model("lenet")
    state = TrainState.create(
        model,
        optim.sgd(0.1),
        jax.random.key(0),
        jnp.zeros((2, 28, 28, 1)),
        ema_decay=0.9,
    )
    state = state.replace(step=jnp.asarray(100, jnp.int32))
    loss_fn = train_loop.classification_loss_fn(model.apply)
    emu = async_ps.AsyncPSEmulator(
        state, loss_fn, async_ps.AsyncConfig(num_workers=2, staleness_limit=2)
    )
    rec = emu.step(_batch(), jax.random.key(1))
    assert rec["staleness"] == 0 and not rec["dropped"]
    changed = any(
        not np.allclose(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(emu.state.ema_params),
            jax.tree.leaves(state.ema_params),
        )
    )
    assert changed, "EMA shadows did not advance"


def test_async_loss_decreases():
    """Async training with staleness still learns on a fixed batch."""
    model, state = _lenet_state(tx=optim.sgd(0.02))
    loss_fn = train_loop.classification_loss_fn(model.apply)
    emu = async_ps.AsyncPSEmulator(
        state, loss_fn, async_ps.AsyncConfig(num_workers=4)
    )
    rng = jax.random.key(0)
    batch = _batch(n=16)
    losses = [
        float(emu.step(batch, rng)["metrics"]["loss"]) for _ in range(30)
    ]
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


# --------------------------------------------------------------------------
# Backup replicas (SURVEY.md §2.4 row 3 — first-N-of-M aggregation)
# --------------------------------------------------------------------------


class TestSyncBackupEmulator:
    def _setup(self, total, aggregate, seed=0):
        from distributed_tensorflow_models_tpu.models import get_model
        from distributed_tensorflow_models_tpu.ops import optim
        from distributed_tensorflow_models_tpu.parallel import backup

        model = get_model("lenet", dropout_rate=0.0)
        tx = optim.sgd(0.1)
        state = TrainState.create(
            model, tx, jax.random.key(0), jnp.zeros((2, 28, 28, 1))
        )
        loss_fn = train_loop.classification_loss_fn(model.apply)
        emu = backup.SyncBackupEmulator(
            state,
            loss_fn,
            backup.BackupConfig(
                total_replicas=total,
                replicas_to_aggregate=aggregate,
                seed=seed,
            ),
        )
        return emu, state, loss_fn

    def _batches(self, n, seed=0):
        rng = np.random.RandomState(seed)
        return {
            "image": rng.rand(n, 28, 28, 1).astype(np.float32),
            "label": rng.randint(0, 10, (n,)),
        }

    def test_full_aggregation_matches_sync_step(self):
        """N == M anchors to the compiled sync step on the global batch:
        mean of per-shard mean-loss gradients == the global-mean gradient."""
        from distributed_tensorflow_models_tpu.parallel import backup

        emu, state, loss_fn = self._setup(total=4, aggregate=4)
        global_batch = self._batches(16)
        shards = backup.split_into_shards(global_batch, 4)
        rng = jax.random.key(7)
        emu.step(shards, rng)

        step_fn = train_loop.make_train_step(loss_fn, donate=False)
        ref_state, _ = step_fn(
            state, jax.tree.map(jnp.asarray, global_batch), rng
        )
        for a, b in zip(
            jax.tree.leaves(emu.state.params),
            jax.tree.leaves(ref_state.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6, rtol=1e-6
            )
        assert emu.discarded == 0

    def test_straggler_gradients_are_discarded(self):
        """The late M-N replicas' data must not influence the update —
        the first-N-win semantics of take_grad(N)."""
        from distributed_tensorflow_models_tpu.parallel import backup

        emu1, _, _ = self._setup(total=3, aggregate=2, seed=5)
        emu2, _, _ = self._setup(total=3, aggregate=2, seed=5)
        rng = jax.random.key(7)
        shards1 = backup.split_into_shards(self._batches(12, seed=1), 3)
        shards2 = [dict(s) for s in shards1]
        rec = emu1.step(shards1, rng)
        (late_idx,) = rec["discarded"]
        # Poison ONLY the discarded replica's batch in the second run.
        shards2[late_idx] = {
            "image": np.zeros_like(shards2[late_idx]["image"]),
            "label": shards2[late_idx]["label"] * 0,
        }
        emu2.step(shards2, rng)
        for a, b in zip(
            jax.tree.leaves(emu1.state.params),
            jax.tree.leaves(emu2.state.params),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert emu1.discarded == emu2.discarded == 1

    def test_config_validation(self):
        from distributed_tensorflow_models_tpu.parallel import backup

        with pytest.raises(ValueError):
            backup.BackupConfig(total_replicas=2, replicas_to_aggregate=3)
        with pytest.raises(ValueError):
            backup.split_into_shards({"x": np.zeros((5, 2))}, 2)
