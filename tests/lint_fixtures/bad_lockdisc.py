"""Known-bad: bare acquire, blocking under a lock, naked wait."""
import queue
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition()
        self._q = queue.Queue()

    def bare(self):
        self._lock.acquire()
        x = self._q.get_nowait()
        self._lock.release()
        return x

    def blocked(self):
        with self._lock:
            return self._q.get()

    def waits(self):
        with self._cond:
            self._cond.wait()
