"""jax-free-zone — supervisor-side modules must not import jax.

``launch.py``, ``resilience/backoff.py``, ``resilience/heartbeat.py``
and every ``scripts/*.py`` run on supervisor hosts (and in the drill
parent process) where the accelerator stack may not exist — and where
importing jax would initialise a backend, pin memory, and race the
child it is about to spawn.  The sanctioned pattern is a *function-
level* lazy import (see ``launch.py``); what this rule forbids is any
**module-level** path from a jax-free root to ``jax`` / ``jaxlib`` /
``flax`` / ``orbax``, even transitively through the package's own
modules and the ``__init__.py`` files that execute on the way in.

Module-level means: top-level statements, including those inside
``if`` / ``try`` / ``with`` blocks and class bodies (all execute at
import time), excluding function bodies and ``if TYPE_CHECKING:``
blocks (which never execute).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Tuple

from analysis.dtmlint.core import Finding, Project

RULE_ID = "jax-free-zone"

_NON_EXEC = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _is_type_checking(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id == "TYPE_CHECKING":
            return True
        if isinstance(node, ast.Attribute) and node.attr == "TYPE_CHECKING":
            return True
    return False


def _module_level_imports(tree: ast.Module) -> Iterator[ast.stmt]:
    """Import statements that execute when the module is imported."""
    stack: List[ast.AST] = [tree]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _NON_EXEC):
                continue
            if isinstance(child, ast.If) and _is_type_checking(child.test):
                continue
            if isinstance(child, (ast.Import, ast.ImportFrom)):
                yield child
            else:
                stack.append(child)


def _ancestor_inits(rel: str, project: Project) -> List[str]:
    """``__init__.py`` files that execute when ``rel`` is imported."""
    out = []
    parts = rel.split("/")
    for i in range(1, len(parts)):
        init = "/".join(parts[:i]) + "/__init__.py"
        if init in project.by_rel and init != rel:
            out.append(init)
    return out


def _rel_to_dotted(project: Project) -> Dict[str, str]:
    return {rel: dotted for dotted, rel in project.module_map.items()}


def _edges(
    rel: str, project: Project, dotted_of: Dict[str, str]
) -> List[Tuple[str, int, Optional[str]]]:
    """``(target_rel_or_None, lineno, forbidden_root_or_None)`` for every
    module-level import edge out of ``rel``."""
    sf = project.by_rel.get(rel)
    if sf is None:
        return []
    forbidden = project.config.forbidden_imports
    edges: List[Tuple[str, int, Optional[str]]] = []

    def classify(dotted: str, lineno: int) -> None:
        root = dotted.split(".")[0]
        if root in forbidden:
            edges.append(("", lineno, root))
            return
        target = project.resolve_module(dotted)
        if target is not None:
            edges.append((target, lineno, None))

    for stmt in _module_level_imports(sf.tree):
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                classify(alias.name, stmt.lineno)
        elif isinstance(stmt, ast.ImportFrom):
            if stmt.level:
                me = dotted_of.get(rel)
                if me is None:
                    continue
                parts = me.split(".")
                # A package's __init__ is one level "shallower" than a
                # plain module for the purposes of relative imports.
                drop = stmt.level - (
                    1 if rel.endswith("__init__.py") else 0
                )
                if drop >= len(parts):
                    continue
                base = parts[: len(parts) - drop] if drop else parts
                prefix = ".".join(base)
                mod = (
                    f"{prefix}.{stmt.module}" if stmt.module else prefix
                )
            else:
                mod = stmt.module or ""
            if not mod:
                continue
            classify(mod, stmt.lineno)
            # ``from pkg import sub`` may bind submodules — chase each
            # name that resolves to a module of ours.
            for alias in stmt.names:
                if alias.name == "*":
                    continue
                sub = f"{mod}.{alias.name}"
                if sub.split(".")[0] in forbidden or (
                    project.resolve_module(sub) is not None
                ):
                    classify(sub, stmt.lineno)
    return edges


def check(project: Project):
    dotted_of = _rel_to_dotted(project)
    edge_cache: Dict[str, List[Tuple[str, int, Optional[str]]]] = {}
    reported = set()

    for root in project.config.jax_free_roots:
        if root not in project.by_rel:
            continue
        # Importing the root executes its ancestor packages first.
        queue: List[Tuple[str, Tuple[str, ...]]] = [(root, (root,))]
        for init in _ancestor_inits(root, project):
            queue.append((init, (root, init)))
        seen = {rel for rel, _ in queue}
        while queue:
            rel, chain = queue.pop(0)
            if rel not in edge_cache:
                edge_cache[rel] = _edges(rel, project, dotted_of)
            for target, lineno, bad in edge_cache[rel]:
                if bad is not None:
                    key = (rel, lineno, bad)
                    if key in reported:
                        continue
                    reported.add(key)
                    via = (
                        " -> ".join(chain)
                        if len(chain) > 1
                        else chain[0]
                    )
                    yield Finding(
                        rel,
                        lineno,
                        RULE_ID,
                        f"module-level `{bad}` import reachable from "
                        f"jax-free root {root} (import chain: {via}); "
                        "use a function-level lazy import",
                    )
                    continue
                hops = [target] + _ancestor_inits(target, project)
                for hop in hops:
                    if hop not in seen:
                        seen.add(hop)
                        queue.append((hop, chain + (hop,)))
