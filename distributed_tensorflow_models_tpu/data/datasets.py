"""Array- and TFRecord-backed datasets for every reference config.

Covers the reference zoo's inputs (SURVEY.md §2.1): MNIST (R3), CIFAR-10
(R4), ImageNet TFRecord shards (R9), and the PTB token stream (R8).  Real
data is loaded when present under ``DATA_DIR`` (``$DTM_DATA_DIR``, default
``/root/data``); otherwise a deterministic synthetic substitute with the
exact shapes/classes is generated, so every pipeline is runnable and
testable in this offline environment.

All iterators expose ``get_state()/set_state()`` for mid-epoch resume —
the capability gap called out in SURVEY.md §5.4 (the reference's queue
pipeline cannot resume; it restarts input from scratch after recovery).

Worker-pool split (``pipeline.py::HostPipeline`` with ``num_workers>1``):
every dataset here additionally factors its iteration into

- ``next_work()`` — advance the *cheap cursor* and return a work
  descriptor for the next batch.  The cursor (epoch/batch position, or
  the TFRecord read head + global record count) is the entire
  checkpointable state; ``next_work`` is the only method that mutates it.
- ``assemble(work)`` — the *pure function* a pool worker executes:
  work descriptor → numpy batch, thread-safe, deterministic (all
  augmentation rngs are derived from positions carried in the work item,
  the reference's many-QueueRunner parallelism made reproducible).

``__iter__`` is defined *through* this split (:func:`iterate_via_work`),
so the serial producer and the worker pool can never diverge — the
emitted stream is bit-identical at any worker count.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Iterator, Optional, Sequence

import numpy as np

from distributed_tensorflow_models_tpu.data import augment, example_proto, tfrecord

DATA_DIR = os.environ.get("DTM_DATA_DIR", "/root/data")


def _validate_process_shard(
    batch_size: int, process_index: int, process_count: int
) -> int:
    """Common multi-host shard validation; returns the local batch size."""
    if batch_size % process_count:
        raise ValueError(
            f"global batch {batch_size} not divisible by "
            f"process count {process_count}"
        )
    if not 0 <= process_index < process_count:
        raise ValueError(f"bad process {process_index}/{process_count}")
    return batch_size // process_count


def iterate_via_work(dataset) -> Iterator[dict[str, np.ndarray]]:
    """Serial iteration expressed through the worker-pool split: pull a
    work item off the cursor, assemble it inline.  Every dataset's
    ``__iter__`` routes through this, so the single-producer path and the
    N-worker pool execute the *same* code and emit the same stream."""
    while True:
        try:
            work = dataset.next_work()
        except StopIteration:
            return
        yield dataset.assemble(work)


# --------------------------------------------------------------------------
# Generic array dataset
# --------------------------------------------------------------------------


class ArrayDataset:
    """Shuffled, checkpointable batch iterator over in-memory arrays.

    Replaces ``shuffle_batch`` over an in-graph queue (TF training/input.py:
    1255 — SURVEY.md §2.2 F10): per-epoch seeded permutation instead of a
    RandomShuffleQueue, so batches are reproducible and the position
    ``(epoch, batch_idx)`` is the full iterator state.

    ``transform(image, rng) -> image`` runs per sample with an rng derived
    from ``(seed, epoch, sample_position)`` — deterministic augmentation.

    Multi-host (SURVEY.md §3.4 — each reference worker feeds its own input
    stream): ``batch_size`` stays the *global* batch; with
    ``process_count > 1`` each process materializes only its
    ``batch_size/process_count`` row block of every global batch, drawn from
    the same seeded permutation.  Process blocks are disjoint and their
    process-order concatenation reproduces the single-process batch exactly
    (``shard_batch`` assembles them in process order), so a multi-process
    run is trajectory-identical to a single-process run at the same global
    batch — the property the 2-process launcher test pins.  Augmentation
    rngs are keyed by *global* sample position, so this holds under
    transforms too.
    """

    def __init__(
        self,
        arrays: dict[str, np.ndarray],
        batch_size: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
        transform: Optional[Callable] = None,
        transform_key: str = "image",
        drop_remainder: bool = True,
        process_index: int = 0,
        process_count: int = 1,
    ):
        sizes = {k: len(v) for k, v in arrays.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"mismatched array lengths {sizes}")
        self._arrays = arrays
        self._n = next(iter(sizes.values()))
        self._batch_size = batch_size
        self._local_batch = _validate_process_shard(
            batch_size, process_index, process_count
        )
        self._local_lo = process_index * self._local_batch
        self._shuffle = shuffle
        self._seed = seed
        self._transform = transform
        self._transform_key = transform_key
        if not drop_remainder and self._n % batch_size:
            raise NotImplementedError("partial final batches unsupported")
        self._epoch = 0
        self._batch_idx = 0
        # Per-epoch permutation cache: assemble() is called from pool
        # worker threads that may straddle an epoch boundary, so the perm
        # is computed once per epoch under a lock (the value is a pure
        # function of (seed, epoch) — any thread computes the same one)
        # and old epochs are pruned to bound memory.
        self._perm_lock = threading.Lock()
        self._perm_cache: dict[int, np.ndarray] = {}

    @property
    def batches_per_epoch(self) -> int:
        return self._n // self._batch_size

    def get_state(self) -> dict:
        return {"epoch": self._epoch, "batch_idx": self._batch_idx}

    def set_state(self, state: dict) -> None:
        self._epoch = int(state["epoch"])
        self._batch_idx = int(state["batch_idx"])

    def _perm_for(self, epoch: int) -> np.ndarray:
        if not self._shuffle:
            return np.arange(self._n)
        with self._perm_lock:
            perm = self._perm_cache.get(epoch)
            if perm is None:
                perm = np.random.RandomState(
                    (self._seed + epoch) & 0x7FFFFFFF
                ).permutation(self._n)
                self._perm_cache[epoch] = perm
                while len(self._perm_cache) > 4:
                    self._perm_cache.pop(min(self._perm_cache))
            return perm

    def next_work(self) -> tuple[int, int]:
        """Advance the cursor; return the ``(epoch, batch_idx)`` position
        the next batch is a pure function of.  Infinite (epochs loop)."""
        if self._batch_idx >= self.batches_per_epoch:
            self._epoch += 1
            self._batch_idx = 0
        work = (self._epoch, self._batch_idx)
        self._batch_idx += 1
        return work

    def assemble(self, work: tuple[int, int]) -> dict[str, np.ndarray]:
        """Pure position → batch (thread-safe; what a pool worker runs).

        Augmentation rngs are keyed by ``(seed, epoch, global sample
        position)`` exactly as the serial path always did, so the batch
        depends only on the work item — never on which worker assembles
        it or in what order."""
        epoch, batch_idx = work
        perm = self._perm_for(epoch)
        lo = batch_idx * self._batch_size + self._local_lo
        idx = perm[lo : lo + self._local_batch]
        batch = {k: v[idx] for k, v in self._arrays.items()}
        if self._transform is not None:
            key = self._transform_key
            out = []
            for j, img in enumerate(batch[key]):
                rng = np.random.default_rng((self._seed, epoch, lo + j))
                out.append(self._transform(img, rng))
            batch[key] = np.stack(out)
        return batch

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        return iterate_via_work(self)


# --------------------------------------------------------------------------
# MNIST / CIFAR-10
# --------------------------------------------------------------------------


def _synthetic_images(n, h, w, c, classes, seed):
    """Class-conditional gaussian blobs: learnable by a small net, so
    loss-decrease integration tests (SURVEY.md §4.4) are meaningful.
    Class means depend only on the *shape* signature, not ``seed``, so a
    model trained on the train split generalizes to the test split."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, n).astype(np.int32)
    means = np.random.RandomState(hash((h, w, c, classes)) & 0x7FFFFFFF).rand(
        classes, 1, 1, c
    ).astype(np.float32)
    images = (
        means[labels]
        + 0.1 * rng.randn(n, h, w, c).astype(np.float32)
    ).clip(0, 1)
    return images.astype(np.float32), labels


def load_mnist(split: str = "train") -> tuple[np.ndarray, np.ndarray]:
    """``[N,28,28,1]`` float32 in [0,1] + int32 labels (R3's input)."""
    path = os.path.join(DATA_DIR, "mnist.npz")
    if os.path.exists(path):
        with np.load(path) as z:
            x = z[f"x_{split}"].astype(np.float32)[..., None] / 255.0
            y = z[f"y_{split}"].astype(np.int32)
            return x, y
    n = 8192 if split == "train" else 1024
    return _synthetic_images(n, 28, 28, 1, 10, seed=1 if split == "train" else 2)


def load_cifar10(split: str = "train") -> tuple[np.ndarray, np.ndarray]:
    """``[N,32,32,3]`` float32 in [0,1] + int32 labels (R4's input)."""
    path = os.path.join(DATA_DIR, "cifar10.npz")
    if os.path.exists(path):
        with np.load(path) as z:
            x = z[f"x_{split}"].astype(np.float32) / 255.0
            y = z[f"y_{split}"].reshape(-1).astype(np.int32)
            return x, y
    n = 8192 if split == "train" else 1024
    return _synthetic_images(n, 32, 32, 3, 10, seed=3 if split == "train" else 4)


def mnist_dataset(
    batch_size: int,
    split: str = "train",
    seed: int = 0,
    *,
    process_index: int = 0,
    process_count: int = 1,
):
    x, y = load_mnist(split)
    return ArrayDataset(
        {"image": x, "label": y},
        batch_size,
        shuffle=split == "train",
        seed=seed,
        process_index=process_index,
        process_count=process_count,
    )


def cifar10_dataset(
    batch_size: int,
    split: str = "train",
    seed: int = 0,
    *,
    process_index: int = 0,
    process_count: int = 1,
):
    x, y = load_cifar10(split)
    transform = (
        augment.preprocess_cifar_train
        if split == "train"
        else lambda img, rng: augment.preprocess_cifar_eval(img)
    )
    return ArrayDataset(
        {"image": x, "label": y},
        batch_size,
        shuffle=split == "train",
        seed=seed,
        transform=transform,
        process_index=process_index,
        process_count=process_count,
    )


# --------------------------------------------------------------------------
# ImageNet TFRecord (R9)
# --------------------------------------------------------------------------


class ImageNetTFRecordDataset:
    """TFRecord shards → decoded, augmented batches (R9 end-to-end).

    Record schema (inception convention): ``image/encoded`` JPEG bytes,
    ``image/class/label`` int64 (1-based in the reference's shards —
    ``label_offset`` subtracts it away), optional ``image/object/bbox/*``.

    Multi-host, the reference's per-worker reader model (SURVEY.md §3.4,
    [TF input.py:1089] — each worker's ``string_input_producer`` consumes
    its own shard files):

    - **train**: shard files round-robin by process
      (``paths[process_index::process_count]``); each process decodes and
      yields only its ``batch_size/process_count`` slice of the global
      batch.  Falls back to replicated-read row-slicing when there are
      fewer shard files than processes.
    - **eval**: every process reads *all* files (one deterministic pass —
      the counting loop of SURVEY.md §3.5 needs a stable global record
      order) and yields its row block of each global batch; the final
      partial batch is padded to the full global size with ``label=-1``
      rows (masked by the padded-batch counting, core/train_loop.py) so
      every process yields equal shapes.
    """

    def __init__(
        self,
        paths: Sequence[str],
        batch_size: int,
        *,
        train: bool = True,
        image_size: int = 224,
        seed: int = 0,
        label_offset: int = 0,
        native: bool | None = None,
        process_index: int = 0,
        process_count: int = 1,
    ):
        self._local_batch = _validate_process_shard(
            batch_size, process_index, process_count
        )
        self._process_index = process_index
        self._process_count = process_count
        # File-sharded mode: this process's stream IS its slice of the
        # global batch, so only local_batch records are decoded per step.
        self._file_sharded = (
            train and process_count > 1 and len(paths) >= process_count
        )
        if self._file_sharded:
            paths = list(paths)[process_index::process_count]
        # Eval is exactly one pass (the reference eval loop counts over the
        # validation set once per checkpoint, SURVEY.md §3.5); training
        # loops epochs forever.
        self._records = tfrecord.ShardedRecordIterator(
            paths,
            shuffle_shards=train,
            seed=seed + (process_index if self._file_sharded else 0),
            native=native,
            num_epochs=None if train else 1,
        )
        self._batch_size = batch_size
        self._train = train
        self._size = image_size
        self._seed = seed
        self._label_offset = label_offset
        self._count = 0
        # Persistent record iterator behind the cursor (created lazily so
        # set_state before first use replays into a fresh one).
        self._rec_it: Optional[Iterator[bytes]] = None
        self._exhausted = False

    def get_state(self) -> dict:
        return {"records": self._records.get_state(), "count": self._count}

    def set_state(self, state: dict) -> None:
        self._records.set_state(state["records"])
        self._count = int(state["count"])
        self._rec_it = None
        self._exhausted = False

    def _parse(self, raw: bytes, count: int) -> tuple[np.ndarray, int]:
        feats = example_proto.parse_example(raw)
        img = augment.decode_jpeg(feats["image/encoded"][0])
        label = int(feats["image/class/label"][0]) - self._label_offset
        bbox = None
        if self._train and feats.get("image/object/bbox/ymin"):
            bbox = np.array(
                [
                    feats["image/object/bbox/ymin"][0],
                    feats["image/object/bbox/xmin"][0],
                    feats["image/object/bbox/ymax"][0],
                    feats["image/object/bbox/xmax"][0],
                ],
                np.float32,
            )
        if self._train:
            # Replicated modes key by global record count so every process
            # derives identical augmentations for the rows it owns
            # (trajectory-match with single-process).  File-sharded mode has
            # per-process counts, so the process index salts the key —
            # without it all hosts would apply identical crop/flip
            # parameters at each within-batch position.
            salt = self._process_index if self._file_sharded else 0
            rng = np.random.default_rng((self._seed, salt, count))
            img = augment.preprocess_imagenet_train(
                img, rng, size=self._size, bbox=bbox
            )
        else:
            img = augment.preprocess_imagenet_eval(img, size=self._size)
        return img.astype(np.float32), label

    def next_work(self) -> dict[str, Any]:
        """Pull the raw records for the next batch off the read head.

        This is the *cheap cursor* half of the pool split: serial record
        I/O plus count bookkeeping, no decode.  The returned work item
        carries ``(raw bytes, global record count)`` pairs — everything
        :meth:`assemble` needs to be a pure function — plus the number of
        ``label=-1`` fill rows (multi-process eval tail only).
        """
        if self._exhausted:
            raise StopIteration
        if self._rec_it is None:
            self._rec_it = iter(self._records)
        items: list[tuple[bytes, int]] = []
        if self._file_sharded:
            # Own shard files == own slice of the global batch; nothing
            # but local records are ever read or decoded.
            for raw in self._rec_it:
                items.append((raw, self._count))
                self._count += 1
                if len(items) == self._local_batch:
                    return {"items": items, "pad": 0}
            # Finite stream ended mid-batch: the ragged train tail is
            # dropped, exactly as the serial loop always did.
            self._exhausted = True
            raise StopIteration

        # Replicated-read modes: all processes see the same global record
        # stream; each keeps only its row block [lo, hi) of every global
        # batch.  ``_count`` advances globally (even past skipped rows), so
        # augmentation rngs agree with a single-process run and the
        # process-order concatenation reproduces its batches exactly.
        lo = self._process_index * self._local_batch
        hi = lo + self._local_batch
        pos = 0
        for raw in self._rec_it:
            if lo <= pos < hi:
                items.append((raw, self._count))
            self._count += 1
            pos += 1
            if pos == self._batch_size:
                return {"items": items, "pad": 0}
        self._exhausted = True
        if pos and not self._train:
            # Partial final global batch so a one-pass eval covers every
            # record.  Single-process: ragged (the eval driver pads).
            # Multi-process: pad every row block to equal shape with
            # label=-1 rows, masked out by the padded-batch counting.
            if self._process_count == 1:
                if items:
                    return {"items": items, "pad": 0}
                raise StopIteration
            return {"items": items, "pad": self._local_batch - len(items)}
        raise StopIteration

    def assemble(self, work: dict[str, Any]) -> dict[str, np.ndarray]:
        """Pure work → batch: JPEG decode + augment for every carried
        record (the expensive half, what a pool worker runs).  Rngs key on
        the global record count inside the work item, so the result is
        independent of assembly order and worker identity."""
        images, labels = [], []
        for raw, count in work["items"]:
            img, label = self._parse(raw, count)
            images.append(img)
            labels.append(label)
        if work["pad"]:
            fill = np.zeros((self._size, self._size, 3), np.float32)
            images.extend([fill] * work["pad"])
            labels.extend([-1] * work["pad"])
        return {
            "image": np.stack(images),
            "label": np.asarray(labels, np.int32),
        }

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        return iterate_via_work(self)


def synthetic_imagenet_dataset(
    batch_size: int,
    image_size: int = 224,
    seed: int = 0,
    *,
    process_index: int = 0,
    process_count: int = 1,
):
    """On-host synthetic ImageNet batches (shapes/classes exact) — the
    throughput-benchmark input, the role slim's fake dataset played for the
    reference's own benchmarking (see bench.py)."""
    x, y = _synthetic_images(
        max(2 * batch_size, 256), image_size, image_size, 3, 1000, seed
    )
    return ArrayDataset(
        {"image": x, "label": y},
        batch_size,
        seed=seed,
        process_index=process_index,
        process_count=process_count,
    )


# --------------------------------------------------------------------------
# PTB (R8)
# --------------------------------------------------------------------------


class PTBDataset:
    """``ptb_producer`` semantics: the token stream is laid out
    ``[batch_size, -1]`` and cut into consecutive ``num_steps`` windows;
    ``targets`` are inputs shifted by one.  Consecutive batches are
    consecutive in the stream, which is what makes threading the LSTM carry
    across steps meaningful (truncated BPTT, SURVEY.md §7.4.5).

    Multi-host: ``batch_size`` is global; each process holds the row block
    ``[process_index*local : (process_index+1)*local]`` of the
    ``[batch_size, -1]`` token layout.  Rows are stable across steps, so
    each process's carry slice stays aligned with its rows, and the
    process-order concatenation equals the single-process batch."""

    def __init__(
        self,
        tokens: np.ndarray,
        batch_size: int,
        num_steps: int,
        *,
        process_index: int = 0,
        process_count: int = 1,
    ):
        local = _validate_process_shard(
            batch_size, process_index, process_count
        )
        n_batches = len(tokens) // batch_size
        data = tokens[: n_batches * batch_size].reshape(batch_size, n_batches)
        data = data[process_index * local : (process_index + 1) * local]
        self._data = data
        self._num_steps = num_steps
        self._epoch_size = (n_batches - 1) // num_steps
        if self._epoch_size <= 0:
            raise ValueError("token stream too short for batch/num_steps")
        self._pos = 0
        self._epoch = 0

    @property
    def batches_per_epoch(self) -> int:
        return self._epoch_size

    def get_state(self) -> dict:
        return {"epoch": self._epoch, "pos": self._pos}

    def set_state(self, state: dict) -> None:
        self._epoch = int(state["epoch"])
        self._pos = int(state["pos"])

    def next_work(self) -> int:
        """Advance the cursor; return the window position the next batch
        is a pure function of.  Infinite (epochs loop)."""
        if self._pos >= self._epoch_size:
            self._epoch += 1
            self._pos = 0
        work = self._pos
        self._pos += 1
        return work

    def assemble(self, work: int) -> dict[str, np.ndarray]:
        """Pure position → window batch (thread-safe; slices only)."""
        T = self._num_steps
        i = work * T
        return {
            "inputs": self._data[:, i : i + T].astype(np.int32),
            "targets": self._data[:, i + 1 : i + T + 1].astype(np.int32),
        }

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        return iterate_via_work(self)


def load_ptb_tokens(split: str = "train", vocab_size: int = 10000) -> np.ndarray:
    """Real PTB ids if ``ptb.{split}.txt`` exists under DATA_DIR (word-level,
    vocab built from the train split), else a synthetic Zipfian stream."""
    path = os.path.join(DATA_DIR, f"ptb.{split}.txt")
    train_path = os.path.join(DATA_DIR, "ptb.train.txt")
    if os.path.exists(path) and os.path.exists(train_path):
        with open(train_path) as f:
            words = f.read().replace("\n", " <eos> ").split()
        from collections import Counter

        vocab = {
            w: i
            for i, (w, _) in enumerate(
                sorted(Counter(words).items(), key=lambda kv: (-kv[1], kv[0]))
            )
        }
        with open(path) as f:
            data = f.read().replace("\n", " <eos> ").split()
        return np.array([vocab[w] for w in data if w in vocab], np.int32)
    rng = np.random.RandomState(5 if split == "train" else 6)
    n = 200_000 if split == "train" else 20_000
    # Zipf-ish distribution over the vocab, clipped into range.
    toks = rng.zipf(1.3, n).astype(np.int64) % vocab_size
    return toks.astype(np.int32)


def ptb_dataset(
    batch_size: int,
    num_steps: int,
    split: str = "train",
    vocab_size: int = 10000,
    *,
    process_index: int = 0,
    process_count: int = 1,
) -> PTBDataset:
    return PTBDataset(
        load_ptb_tokens(split, vocab_size),
        batch_size,
        num_steps,
        process_index=process_index,
        process_count=process_count,
    )
