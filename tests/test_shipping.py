"""KV-shipping wire format, handoff protocol, fleet prefix index.

The disaggregated serving fleet's jax-free substrate
(``serving/shipping.py``), pinned at tier-1 speed:

- the versioned wire format round-trips bit-exactly, is a pure
  function of its contents, and rejects — with :class:`ShipError`,
  never garbage — truncation, corruption, wrong magic/version, 64-bit
  metadata, and non-wire dtypes;
- the handoff dir's atomic-rename claim protocol is exactly-once under
  concurrent decode replicas, with unclaim (the SIGTERM drain path)
  returning a bundle to the claimable pool;
- the fleet-wide prefix index's chain digests commit to the full token
  prefix, advertise is publish-if-absent (concurrent twins dedupe),
  lookup returns the longest advertised prefix, and eviction races
  read as misses, never errors.

No jax anywhere — everything here must hold on a login host.
"""

import os
import threading

import numpy as np
import pytest

from distributed_tensorflow_models_tpu.serving import shipping
from distributed_tensorflow_models_tpu.serving.shipping import (
    FleetPrefixIndex,
    ShipError,
    bundle_name,
    claim_bundle,
    mark_prefill_done,
    pack_bundle,
    prefill_done_count,
    publish_bundle,
    unclaim_bundle,
    unpack_bundle,
)


def _leaves():
    return {
        "layers/0/k": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
        "layers/0/v": np.ones((2, 3, 4), np.float16),
        "tables": np.arange(6, dtype=np.int32).reshape(2, 3),
        "mask": np.array([True, False, True]),
    }


META = {
    "kind": "request",
    "request_id": 7,
    "prompt": [1, 2, 3],
    "nested": {"cached_len": 0, "flags": [1, 0, 1]},
}


# --------------------------------------------------------------------------
# Wire format
# --------------------------------------------------------------------------


def test_wire_roundtrip_bit_exact():
    data = pack_bundle(META, _leaves())
    meta, leaves = unpack_bundle(data)
    assert meta == META
    assert sorted(leaves) == sorted(_leaves())
    for path, want in _leaves().items():
        got = leaves[path]
        assert got.dtype == want.dtype and got.shape == want.shape
        assert np.array_equal(got, want)
    # Pure function of contents: identical bundles are identical bytes.
    assert pack_bundle(META, _leaves()) == data


def test_wire_rejects_int64_meta():
    with pytest.raises(ShipError, match="int32"):
        pack_bundle({"page_id": 1 << 40}, {})
    with pytest.raises(ShipError, match="int32"):
        pack_bundle({"ids": [1, 2, {"deep": -(1 << 35)}]}, {})
    # Bools are not integers for wire purposes, and int32 extremes fit.
    pack_bundle({"ok": True, "lo": -(2**31), "hi": 2**31 - 1}, {})


def test_wire_rejects_non_wire_dtypes():
    with pytest.raises(ShipError, match="wire-safe"):
        pack_bundle({}, {"pages": np.arange(4, dtype=np.int64)})
    with pytest.raises(ShipError, match="wire-safe"):
        pack_bundle({}, {"pages": np.arange(4, dtype=np.float64)})


def test_wire_rejects_truncation_at_every_cut():
    data = pack_bundle(META, _leaves())
    # Any strict prefix must be rejected — the trailer pins the exact
    # length, so no cut point can masquerade as a complete bundle.
    for cut in (0, 1, len(shipping.MAGIC), len(data) // 2, len(data) - 1):
        with pytest.raises(ShipError):
            unpack_bundle(data[:cut])
    with pytest.raises(ShipError):
        unpack_bundle(data + b"\0")  # appended junk is not a bundle either


def test_wire_rejects_corruption_anywhere():
    data = pack_bundle(META, _leaves())
    for pos in (0, len(shipping.MAGIC) + 6, len(data) // 2, len(data) - 9):
        corrupt = bytearray(data)
        corrupt[pos] ^= 0xFF
        with pytest.raises(ShipError):
            unpack_bundle(bytes(corrupt))


def test_wire_rejects_wrong_version(monkeypatch):
    monkeypatch.setattr(shipping, "WIRE_VERSION", shipping.WIRE_VERSION + 1)
    data = pack_bundle(META, _leaves())
    monkeypatch.undo()
    with pytest.raises(ShipError, match="version"):
        unpack_bundle(data)


# --------------------------------------------------------------------------
# Handoff protocol
# --------------------------------------------------------------------------


def test_publish_claim_roundtrip(tmp_path):
    handoff = str(tmp_path / "handoff")
    data = pack_bundle(META, _leaves())
    path = publish_bundle(handoff, META["request_id"], data, chunk_bytes=7)
    assert os.path.basename(path) == bundle_name(META["request_id"])
    assert not [n for n in os.listdir(handoff) if n.endswith(".tmp")]
    got = claim_bundle(handoff, replica=1)
    assert got is not None
    name, meta, leaves = got
    assert name == bundle_name(META["request_id"])
    assert meta == META
    assert np.array_equal(leaves["tables"], _leaves()["tables"])
    # Claimed exactly once: nothing left for a second claimant.
    assert claim_bundle(handoff, replica=2) is None


def test_claims_are_exactly_once_under_concurrency(tmp_path):
    handoff = str(tmp_path / "handoff")
    n_bundles, n_replicas = 24, 4
    for rid in range(n_bundles):
        publish_bundle(handoff, rid, pack_bundle({"request_id": rid}, {}))
    claimed: list = [[] for _ in range(n_replicas)]
    barrier = threading.Barrier(n_replicas)

    def run(replica):
        barrier.wait()
        while True:
            got = claim_bundle(handoff, replica)
            if got is None:
                return
            claimed[replica].append(got[1]["request_id"])

    threads = [
        threading.Thread(target=run, args=(i,)) for i in range(n_replicas)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    all_rids = [rid for per in claimed for rid in per]
    assert sorted(all_rids) == list(range(n_bundles))  # no dup, no drop
    audit = os.listdir(os.path.join(handoff, shipping.CLAIMED_DIR))
    assert len(audit) == n_bundles


def test_unclaim_returns_bundle_to_pool(tmp_path):
    handoff = str(tmp_path / "handoff")
    publish_bundle(handoff, 3, pack_bundle({"request_id": 3}, {}))
    name, _, _ = claim_bundle(handoff, replica=0)
    assert claim_bundle(handoff, replica=1) is None
    unclaim_bundle(handoff, name, replica=0)  # SIGTERM between claim+adopt
    got = claim_bundle(handoff, replica=1)
    assert got is not None and got[1]["request_id"] == 3


def test_prefill_done_markers_idempotent(tmp_path):
    handoff = str(tmp_path / "handoff")
    assert prefill_done_count(handoff) == 0
    mark_prefill_done(handoff, 0)
    mark_prefill_done(handoff, 0)  # re-mark on a retried drain is benign
    assert prefill_done_count(handoff) == 1
    mark_prefill_done(handoff, 1)
    assert prefill_done_count(handoff) == 2
    # Markers are not claimable bundles.
    assert claim_bundle(handoff, replica=0) is None


# --------------------------------------------------------------------------
# Fleet-wide prefix index
# --------------------------------------------------------------------------


def _page_leaves(fill):
    return {"k": np.full((2, 4), fill, np.float32),
            "v": np.full((2, 4), -fill, np.float32)}


def test_fleet_chain_digest_commits_to_full_prefix(tmp_path):
    idx = FleetPrefixIndex(str(tmp_path / "fleet"), page_tokens=2)
    a = idx.chain_digests([(1, 2), (3, 4)])
    b = idx.chain_digests([(1, 2), (3, 5)])
    c = idx.chain_digests([(9, 2), (3, 4)])
    assert a[0] == b[0]  # shared first page, shared digest
    assert a[1] != b[1]  # second page differs
    assert a[0] != c[0] and a[1] != c[1]  # digest(1) commits to page 0 too
    other = FleetPrefixIndex(str(tmp_path / "fleet2"), page_tokens=4)
    assert other.chain_digests([(1, 2)]) != idx.chain_digests([(1, 2)])


def test_fleet_advertise_lookup_longest_prefix(tmp_path):
    idx = FleetPrefixIndex(str(tmp_path / "fleet"), page_tokens=2)
    pages = [(1, 2), (3, 4)]
    leaves = [_page_leaves(0.5), _page_leaves(1.5)]
    assert idx.any_missing(pages)
    assert idx.advertise(pages, leaves) == 2
    assert not idx.any_missing(pages)
    assert idx.entry_count() == 2
    # Re-advertising is publish-if-absent: zero new entries.
    assert idx.advertise(pages, leaves) == 0
    found = idx.lookup(pages)
    assert len(found) == 2
    assert np.array_equal(found[1]["k"], leaves[1]["k"])
    # A diverging second page hits only the shared first page.
    assert len(idx.lookup([(1, 2), (9, 9)])) == 1
    assert idx.lookup([(7, 7)]) == []


def test_fleet_rejects_int64_tokens(tmp_path):
    idx = FleetPrefixIndex(str(tmp_path / "fleet"), page_tokens=2)
    with pytest.raises(ShipError, match="int32"):
        idx.chain_digests([(1, 1 << 40)])


def test_fleet_eviction_reads_as_miss(tmp_path):
    idx = FleetPrefixIndex(str(tmp_path / "fleet"), page_tokens=2)
    pages = [(i, i + 1) for i in range(0, 8, 2)]
    leaves = [_page_leaves(float(i)) for i in range(4)]
    assert idx.advertise(pages, leaves) == 4
    # Evict the OLDEST entries; mtime order may tie within one call, so
    # just pin the capacity invariant + that lookup degrades to a
    # shorter (possibly empty) prefix instead of erroring.
    assert idx.evict(down_to=2) == 2
    assert idx.entry_count() == 2
    found = idx.lookup(pages)
    assert len(found) <= 2  # never longer than what is resident
    # A vanished entry mid-walk (concurrent evictor) is a miss.
    for name in os.listdir(idx.root):
        os.unlink(os.path.join(idx.root, name))
    assert idx.lookup(pages) == []
    assert idx.evict(down_to=0) == 0  # double-evict is benign


def test_fleet_capacity_bound_applied_on_advertise(tmp_path):
    idx = FleetPrefixIndex(
        str(tmp_path / "fleet"), page_tokens=2, max_entries=3
    )
    for i in range(5):
        idx.advertise([(10 * i, 10 * i + 1)], [_page_leaves(float(i))])
    assert idx.entry_count() <= 3


def test_fleet_concurrent_advertise_dedupes(tmp_path):
    idx = FleetPrefixIndex(str(tmp_path / "fleet"), page_tokens=2)
    pages = [(1, 2), (3, 4), (5, 6)]
    leaves = [_page_leaves(float(i)) for i in range(3)]
    totals = []
    barrier = threading.Barrier(4)

    def run():
        barrier.wait()
        totals.append(idx.advertise(pages, leaves))

    threads = [threading.Thread(target=run) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert idx.entry_count() == 3
    assert idx.lookup(pages) and len(idx.lookup(pages)) == 3


# --------------------------------------------------------------------------
# Clock rebase
# --------------------------------------------------------------------------


def test_clock_rebase_is_inverse_within_tolerance():
    import time

    t = time.perf_counter()
    assert abs(shipping.mono_of_wall(shipping.wall_of_mono(t)) - t) < 0.05
