"""determinism-hazard — no ambient entropy in replayed state.

The bit-identical-recovery contract says: restore a checkpoint, replay
the same steps, get the same bytes.  That dies the moment anything
feeding checkpointed state, dataset cursors, or replay decisions reads
a wall clock or an unseeded RNG — the restored run sees different
values than the original did.  In the scoped modules (data pipeline /
cursors, the train loop, chaos scheduling, the async-PS and backup
paths) this rule forbids:

- wall-clock reads: ``time.time`` / ``time.time_ns`` /
  ``time.monotonic`` / ``time.monotonic_ns`` (``time.perf_counter`` is
  the allowlisted telemetry-timing primitive — it measures durations,
  its value never flows into state);
- ambient entropy: ``os.urandom``, ``uuid.uuid1/uuid4``,
  ``secrets.*``;
- process-global RNGs: any ``random.*`` call, any ``np.random.*``
  module-level call;
- unseeded RNG construction: ``np.random.RandomState()`` /
  ``np.random.default_rng()`` with no seed (seeded constructors are the
  sanctioned pattern — every existing site passes ``config.seed``).

Out-of-scope modules (telemetry, harness supervision) may use wall
clocks freely; this rule only runs over ``determinism_scope``.
"""

from __future__ import annotations

import ast

from analysis.dtmlint.astutil import dotted_name
from analysis.dtmlint.core import Finding, Project

RULE_ID = "determinism-hazard"

_FORBIDDEN_EXACT = {
    "time.time": "wall clock",
    "time.time_ns": "wall clock",
    "time.monotonic": "process-relative clock (differs across restore)",
    "time.monotonic_ns": "process-relative clock (differs across restore)",
    "os.urandom": "ambient entropy",
    "uuid.uuid1": "ambient entropy",
    "uuid.uuid4": "ambient entropy",
}

_SEEDABLE_CTORS = frozenset(
    {"RandomState", "default_rng", "Generator", "PCG64", "Philox"}
)


def _has_seed(call: ast.Call) -> bool:
    if any(not isinstance(a, ast.Starred) for a in call.args):
        return True
    return any(
        kw.arg in ("seed", "key") or kw.arg is None for kw in call.keywords
    )


def _classify(call: ast.Call):
    """``(why, detail)`` when the call is a hazard, else None."""
    dn = dotted_name(call.func)
    if dn is None:
        return None
    if dn in _FORBIDDEN_EXACT:
        return dn, _FORBIDDEN_EXACT[dn]
    parts = dn.split(".")
    if parts[0] == "secrets":
        return dn, "ambient entropy"
    if parts[0] == "random" and len(parts) == 2:
        return dn, "process-global RNG (unseeded across restore)"
    if len(parts) >= 3 and parts[0] in ("np", "numpy") and (
        parts[1] == "random"
    ):
        tail = parts[2]
        if tail in _SEEDABLE_CTORS:
            if _has_seed(call):
                return None
            return dn, "unseeded RNG constructor"
        return dn, "module-level global RNG"
    return None


def check(project: Project):
    scope = set(project.config.determinism_scope)
    for sf in project.scoped_files:
        if sf.rel not in scope:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            hit = _classify(node)
            if hit is None:
                continue
            dn, why = hit
            yield Finding(
                sf.rel,
                node.lineno,
                RULE_ID,
                f"`{dn}` ({why}) in a determinism-scoped module; "
                "values here feed checkpointed state or replay "
                "decisions — derive from step/seed instead",
            )
