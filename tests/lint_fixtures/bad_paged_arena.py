"""Known-bad: paged-arena hazards — a traced length used as a gather
view's SHAPE (recompile per length), and the donated pool read/reused
without the same-statement rebind the paged protocol requires.

No module-level jax import on purpose (fixtures are linted as jax-free
roots in strict mode); nothing here is ever executed.
"""


def gather_view(pool, table, length):
    pages = pool[table]
    view = pages.reshape(1, length, 4)
    return view


class PagedEngine:
    def __init__(self, fn):
        self._prefill = jax.jit(fn, donate_argnums=(1,))

    def run(self, params, pool, tables):
        out = self._prefill(params, pool, tables)
        stale = pool.sum()
        return out, stale

    def waves(self, params, pool, waves):
        out = None
        for wave in waves:
            out = self._prefill(params, pool, wave)
        return out


gather_j = jax.jit(gather_view)
