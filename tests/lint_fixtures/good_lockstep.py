"""Known-good twins: uniform predicates and matched branches."""


def uniform_guard(consensus, nproc, value):
    if nproc == 1:
        return [value]
    return consensus.allgather_int(value)


def matched_fallthrough(consensus, is_chief, value):
    if is_chief:
        return consensus.broadcast_int(value)
    return consensus.broadcast_int(0)


def collective_in_test(consensus, is_chief, failed):
    # The collective runs *before* the branch — every host enters it.
    if consensus.any_flag(failed):
        return "rollback" if is_chief else "wait"
    return "ok"
