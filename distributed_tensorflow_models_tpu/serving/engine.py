"""Paged inference engine: two compiled programs, bit-identical sampling.

Ties the paged KV arena (:mod:`.kv_slots`) and the radix prefix cache
(:mod:`.prefix_cache`) to the existing transformer decode path
(``models/transformer_lm.py`` ``decode=True``) under two jitted
programs whose shapes never depend on traffic:

- **prefill** — ONE batched dispatch for up to ``prefill_lanes``
  requests: each lane gathers its cache view through its block table,
  runs one ``prefill_chunk``-token right-padded chunk of its prompt
  (traced per-lane start/last indices), and every touched page
  scatters back to the pool in a single flattened write.  An admission
  burst prefills many prompts per dispatch instead of serially; lanes
  beyond the burst ride along inert (all-sentinel tables, zero
  tokens).  Sampling runs per lane so the final chunk's lane returns
  the request's first generated token.
- **decode** — ONE batched dispatch for ALL slots over a *persistent
  working set*: the engine keeps one resident contiguous view per slot
  (:func:`.kv_slots.make_views`), donated in and out of every dispatch.
  A lane re-adopts its view from the pool (gather through its block
  table, :func:`.kv_slots.adopt_lanes`) only on the first dispatch
  after its prefill — one ``lax.cond`` over the whole working set,
  gated on any lane needing adoption, so steady-state dispatches
  execute the identity branch and copy nothing.  The unmodified B=1 single-token
  apply (vmapped over lanes) then advances the views ``decode_burst``
  tokens by an in-program ``lax.scan`` (each lane's sample feeds
  straight back as its next input token — the same autoregressive
  recurrence ``generate()`` runs).  Decode never writes the pool: the
  prefix cache shares only PROMPT pages (written by prefill), so
  decode-written suffix positions are never read from the pool by
  anyone, and steady-state decode pays zero gather/scatter — the same
  per-dispatch cost as a dedicated-slot engine.

Block tables, lengths, and key material are DATA (padded int32/uint32
arrays); admission, prefix sharing, copy-on-write, retirement, and
block recycling only change their values.  ``tests/test_serving.py``
pins ``_cache_size() == 1`` for both programs after mixed workloads at
several page sizes: paging and prefix caching add zero compiled
programs.

**Why paging cannot move a bit.**  Each lane's adopted view is
byte-for-byte the ``[1, max_len, ...]`` cache a dedicated slot would
have held (gather through the block table, then advanced in place
across dispatches exactly as a dedicated slot's cache would be), and
the model apply over it is unmodified — same reduction shapes and order as the slotted engine,
and as solo ``generate()`` (decode attention always reduces over the
full ``max_len`` view with masked scores exactly zeroed; constant
reduction length, so batch composition, page size, and table layout
cannot change a single bit).  Right-padding is sound for the same
reason it was in PR 10: garbage K/V written at padded positions is
strictly after every real query position (causally masked), lands in
the lane's own private or sentinel blocks — never in a shared resident
block (shared pages sit strictly below the prefill start and the
decode write head) — and every later read of a real position happens
only after real K/V overwrote it.  Counters are reconstructed from
host-tracked true lengths around each apply, so the model's
``dynamic_update_slice`` writes and RoPE rotations see exactly the
positions solo decoding would.

**Warm-prefix reuse is exact**, not approximate: the per-position K/V
a prefill writes is bitwise invariant to how the prompt was chunked and
to what followed it (each position's projection reads only that
position's embedding; attention never feeds back into the cache), so a
resident block holds exactly the bytes the new request's own prefill
would have produced, and skipping the cached prefix leaves the stream
byte-identical at any cache warmth — the contract
``tests/test_serving.py`` pins cold, warm, and mid-divergence.

**Bit-identity of sampling.**  :func:`sample_dynamic` recomputes
``generate()``'s ``_filter_logits`` + ``_sample`` with (temperature,
top_k, top_p) as *traced per-lane values* instead of Python statics,
gated by ``jnp.where`` so one compiled program serves every sampling
mode.  Each gate is exact: top_k off ⇒ a -inf threshold masks nothing;
top_p off ⇒ the nucleus mask is bypassed wholesale; greedy ⇒ argmax of
the unscaled row.  Per-request keys are precomputed via
:func:`~..harness.generate.key_schedule` — the exact
``jax.random.split(rng, max_new)`` schedule ``generate()`` uses — so a
request's token stream is bit-identical to a solo ``generate()`` run
regardless of what it was batched with.
"""

from __future__ import annotations

import math
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from distributed_tensorflow_models_tpu.harness.generate import key_schedule
from distributed_tensorflow_models_tpu.serving import kv_slots
from distributed_tensorflow_models_tpu.serving.prefix_cache import (
    RadixPrefixCache,
    prompt_pages,
)
from distributed_tensorflow_models_tpu.telemetry import registry as reglib


def sample_dynamic(row, keydata, temperature, top_k, top_p, dtype):
    """One sampling decision with TRACED sampling knobs, bit-identical to
    ``generate.py``'s static ``_sample(_filter_logits(...))`` for every
    knob setting (pinned in tests).

    ``row`` is the unscaled float32 logits row ``[V]``; ``keydata`` the
    raw ``jax.random.key_data`` row for this token (unused bits cost
    nothing under the greedy gate).  Returns a scalar token of ``dtype``.
    """
    v = row.shape[-1]
    safe_t = jnp.where(temperature > 0, temperature, jnp.float32(1.0))
    # [1, V] to mirror generate()'s batch-of-one categorical exactly
    # (same shape -> same sampling bits).
    scaled = (row / safe_t)[None, :]
    sorted_ = jnp.sort(scaled, axis=-1)[..., ::-1]
    # top-k threshold: the k-th largest of the scaled row; disabled
    # (top_k <= 0) degrades to a -inf threshold that masks nothing.
    idx = (jnp.clip(top_k, 1, v) - 1).astype(jnp.int32)
    kth = jnp.take_along_axis(sorted_, idx[None, None], axis=-1)
    kth = jnp.where(top_k > 0, kth, -jnp.inf)
    filtered = jnp.where(scaled < kth, -jnp.inf, scaled)
    # Nucleus mass over the top-k-filtered distribution (sequential
    # top-k-then-top-p semantics, as in _filter_logits).
    sorted_m = jnp.where(sorted_ < kth, -jnp.inf, sorted_)
    probs = jax.nn.softmax(sorted_m, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = (cum - probs < top_p).at[..., 0].set(True)
    cutoff = jnp.min(
        jnp.where(keep, sorted_m, jnp.inf), axis=-1, keepdims=True
    )
    filtered = jnp.where(
        top_p < 1.0,
        jnp.where(scaled < cutoff, -jnp.inf, filtered),
        filtered,
    )
    key = jax.random.wrap_key_data(keydata)
    sampled = jax.random.categorical(key, filtered, axis=-1)[0]
    greedy = jnp.argmax(row[None, :], axis=-1)[0]
    return jnp.where(temperature > 0, sampled, greedy).astype(dtype)


class InferenceEngine:
    """The device half of serving: paged pool + the two jitted programs.

    ``model`` is the TRAINING-configured ``TransformerLM`` (re-cloned
    here with ``decode=True``, like ``generate()``); ``params`` its
    trained parameters.  The engine owns the pool, the block allocator,
    the prefix cache, and the :class:`~.kv_slots.SlotManager`; the
    scheduler decides WHICH requests get admitted, the engine moves
    tokens and blocks.

    The pool is donated to both jitted programs, so each step updates
    it in place (no second pool's worth of HBM) — callers must treat
    ``self.pool`` as consumed across calls, which the engine does
    internally by always rebinding it in the same statement as the
    dispatch.

    Admission is two-resource: a decode lane (slot) AND enough free
    blocks for the request's whole reservation,
    ``ceil((prompt + max_new) / kv_page_tokens)`` pages, taken up front
    (minus whatever the prefix cache already holds) so a request can
    never be stranded mid-decode by pool exhaustion — exhaustion is
    admission backpressure, not preemption.
    """

    def __init__(
        self,
        model,
        params,
        *,
        max_slots: int = 8,
        prefill_chunk: int = 32,
        decode_burst: int = 1,
        prefill_lanes: int = 1,
        kv_page_tokens: Optional[int] = None,
        kv_pool_blocks: Optional[int] = None,
        prefix_cache: bool = True,
        prefix_cache_blocks: Optional[int] = None,
        spec_tokens: int = 0,
        spec_ngram_order: int = 3,
        spec_min_match: int = 1,
        registry: Optional[reglib.MetricsRegistry] = None,
        fleet_cache=None,
    ):
        if decode_burst < 1:
            raise ValueError(
                f"decode_burst must be >= 1, got {decode_burst}"
            )
        if spec_tokens < 0:
            raise ValueError(
                f"spec_tokens must be >= 0, got {spec_tokens}"
            )
        if spec_tokens + 1 > model.max_len:
            raise ValueError(
                f"spec_tokens {spec_tokens} leaves no room for real "
                f"tokens in max_len {model.max_len}"
            )
        if spec_tokens and spec_min_match < 1:
            raise ValueError(
                f"spec_min_match must be >= 1, got {spec_min_match}"
            )
        if spec_tokens and spec_ngram_order < spec_min_match:
            raise ValueError(
                f"spec_ngram_order {spec_ngram_order} must be >= "
                f"spec_min_match {spec_min_match}"
            )
        if prefill_chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1, got {prefill_chunk}"
            )
        if prefill_chunk > model.max_len:
            raise ValueError(
                f"prefill_chunk {prefill_chunk} exceeds model max_len "
                f"{model.max_len}"
            )
        if prefill_lanes < 1:
            raise ValueError(
                f"prefill_lanes must be >= 1, got {prefill_lanes}"
            )
        self.model = model
        self.params = params
        # Weight-version ledger (continuous deployment).  ``version`` is
        # the primary weight version (checkpoint step; 0 = boot weights)
        # and ``self.params`` always aliases its tree.  A canary is a
        # second live version serving a routed traffic slice; every slot
        # is pinned at admission to the version it was routed to and
        # keeps those exact weights until it retires — that pin is what
        # makes an in-flight stream byte-identical to a solo generate()
        # with its admitted weights, no matter when a swap lands.
        # Retired versions are pruned once no slot references them.
        self.version = 0
        self.canary_version: Optional[int] = None
        self._versions: dict = {0: params}
        self._slot_version: dict = {}  # slot -> version pinned at admit
        self._deploy_active = False  # ever canaried: per-version metrics on
        self.max_slots = int(max_slots)
        self.prefill_chunk = int(prefill_chunk)
        self.decode_burst = int(decode_burst)
        self.prefill_lanes = int(prefill_lanes)
        # Speculative decoding (off at 0): spec_tokens is a
        # CONSTRUCTION-TIME constant exactly like decode_burst — the
        # verify window width (spec_tokens + 1) is baked into the one
        # decode entry point's second traced instance, never derived
        # from traffic (see _decode_fn and compile_counts).
        self.spec_tokens = int(spec_tokens)
        self.spec_ngram_order = int(spec_ngram_order)
        self.spec_min_match = int(spec_min_match)
        self.max_len = int(model.max_len)
        if kv_page_tokens is None:
            # Largest page that both divides max_len (tables must tile
            # it exactly) and divides prefill_chunk or vice versa —
            # gcd satisfies both and degrades gracefully for any pair.
            kv_page_tokens = math.gcd(self.max_len, self.prefill_chunk)
        if kv_page_tokens < 1 or self.max_len % kv_page_tokens != 0:
            raise ValueError(
                f"kv_page_tokens {kv_page_tokens} must be >= 1 and "
                f"divide max_len {self.max_len}"
            )
        self.kv_page_tokens = int(kv_page_tokens)
        self._page = self.kv_page_tokens
        self._bps = self.max_len // self._page  # table width (blocks/seq)
        if kv_pool_blocks is None:
            # Sentinel + a full max_len reservation per slot: the paged
            # default can admit at least everything the slotted arena
            # could, and the prefix cache only adds headroom on top.
            kv_pool_blocks = self.max_slots * self._bps + 1
        if kv_pool_blocks < self._bps + 1:
            raise ValueError(
                f"kv_pool_blocks {kv_pool_blocks} cannot hold one "
                f"max_len sequence ({self._bps} blocks + sentinel)"
            )
        self.num_blocks = int(kv_pool_blocks)
        self.registry = registry if registry is not None else reglib.get_registry()
        self._ensure_spec_metrics()
        self.slots = kv_slots.SlotManager(max_slots)
        self.blocks = kv_slots.BlockPool(self.num_blocks)
        self.prefix_cache = (
            RadixPrefixCache(
                self.blocks, self._page, max_blocks=prefix_cache_blocks
            )
            if prefix_cache else None
        )
        self._evictions_seen = 0  # cache.evictions already mirrored
        # Fleet-wide prefix index (shipping.FleetPrefixIndex, or any
        # object with the same chain-digest lookup/advertise surface).
        # A resident prefix on ANY prefill replica serves the whole
        # fleet: admission consults the index for pages the local trie
        # misses (adopting them into the local trie, so the normal
        # match path below reuses them), and prefill advertises freshly
        # resident pages.  Requires the local prefix cache — adopted
        # pages live in the trie like any other resident prefix.
        if fleet_cache is not None and not prefix_cache:
            raise ValueError(
                "fleet_cache requires prefix_cache=True (fleet pages "
                "are adopted into the local radix trie)"
            )
        self.fleet_cache = fleet_cache
        self._decode_model = model.clone(decode=True, dropout_rate=0.0)
        self.pool = kv_slots.make_pool(
            self._decode_model, self.num_blocks, self._page
        )
        # Decode working set: one resident contiguous view per slot,
        # donated through every decode dispatch.  _views_fresh[s] marks
        # "the pool holds newer bytes than slot s's view" (set when a
        # prefill completes, cleared when decode adopts the lane).
        self._views = kv_slots.make_views(
            self._decode_model, self.max_slots, self.max_len
        )
        self._views_fresh = np.zeros((self.max_slots,), bool)
        # Host mirrors of per-slot device inputs: block-table rows and
        # true sequence lengths (counters are derived from these on
        # every dispatch — the pool itself holds no positions).
        self._tables = np.zeros((self.max_slots, self._bps), np.int32)
        self._lengths = np.zeros((self.max_slots,), np.int32)
        self._slot_blocks: dict = {}  # slot -> blocks this request holds
        self._slot_cached: dict = {}  # slot -> cached prefix length
        # Key-material layout for this backend's PRNG impl (threefry:
        # uint32[2] per key) — probed, not hardcoded, so an rbg/unsafe
        # impl switch keeps working.
        kd = np.asarray(jax.random.key_data(jax.random.key(0)))
        self._key_shape = kd.shape
        self._key_dtype = kd.dtype
        self._prefill_j = jax.jit(self._prefill_fn, donate_argnums=(1,))
        self._decode_j = jax.jit(self._decode_fn, donate_argnums=(1,))

    # -- request bookkeeping helpers --------------------------------------

    def _ensure_spec_metrics(self) -> None:
        """Pre-create the speculation metrics so zero is observable (a
        spec-on engine that never verified still reports the full
        ``serve/spec_*`` set); a spec-off engine creates NONE of them,
        leaving the spec-off registry byte-for-byte unchanged.
        Idempotent — the server re-invokes it after adopting the engine
        into its own registry."""
        if not self.spec_tokens:
            return
        self.registry.counter(reglib.SERVE_SPEC_DRAFTED)
        self.registry.counter(reglib.SERVE_SPEC_ACCEPTED)
        self.registry.timer(reglib.SERVE_SPEC_ACCEPTANCE_RATE)
        self.registry.timer(reglib.SERVE_SPEC_TOKENS_PER_DISPATCH)

    def padded_len(self, prompt_len: int) -> int:
        """Positions a cold prompt occupies after right-padded chunking."""
        c = self.prefill_chunk
        return -(-prompt_len // c) * c

    def padded_suffix(self, prompt_len: int, cached_len: int = 0) -> int:
        """Positions the UNCACHED tail of a prompt occupies after
        right-padded chunking from ``cached_len`` — the prefill work a
        warm request actually pays (and what admission budgets)."""
        c = self.prefill_chunk
        return -(-(prompt_len - cached_len) // c) * c

    def check_fits(self, prompt_len: int, max_new_tokens: int) -> None:
        """Admission bound: real tokens AND the cold padded prefill
        footprint must fit in ``max_len`` (a clamped final-chunk write
        would corrupt real positions — module docstring).  Cold is the
        worst case; warm admission only shrinks the footprint
        (:meth:`_usable_cached_len` re-checks at the actual warmth)."""
        if prompt_len < 1:
            raise ValueError("prompt must be non-empty")
        total = prompt_len + max_new_tokens
        if total > self.max_len:
            raise ValueError(
                f"prompt {prompt_len} + new {max_new_tokens} exceeds "
                f"max_len {self.max_len}"
            )
        # With speculation on, the verify window (spec_tokens + 1 wide,
        # static) can start as late as position total - 1, so the table
        # needs spec_tokens positions of headroom past the real tokens
        # — otherwise the window's clamped dynamic_update_slice write
        # would slide back over real positions (same hazard as the
        # padded final prefill chunk below).
        if self.spec_tokens and total + self.spec_tokens > self.max_len:
            raise ValueError(
                f"prompt {prompt_len} + new {max_new_tokens} + "
                f"spec_tokens {self.spec_tokens} headroom exceeds "
                f"max_len {self.max_len}"
            )
        if self.padded_len(prompt_len) > self.max_len:
            raise ValueError(
                f"padded prompt {self.padded_len(prompt_len)} "
                f"(chunk {self.prefill_chunk}) exceeds max_len "
                f"{self.max_len}"
            )

    def request_keys(self, rng, max_new_tokens: int) -> np.ndarray:
        """Per-token key material, ``[max_new_tokens, *key_shape]`` —
        exactly ``generate()``'s ``key_schedule`` (the shared helper),
        so token i of this request samples with the same key solo
        decoding would have used."""
        keys = key_schedule(rng, max_new_tokens)
        return np.asarray(jax.random.key_data(keys))

    def zero_keys(self, max_new_tokens: int) -> np.ndarray:
        """Placeholder key material for greedy requests (the categorical
        branch is computed then discarded by the greedy gate)."""
        return np.zeros(
            (max_new_tokens,) + self._key_shape, self._key_dtype
        )

    # -- block/prefix admission --------------------------------------------

    def _matchable(self, prompt) -> list:
        """The prompt's shareable pages: full pages only, and never the
        final page of an exactly-page-aligned prompt — at least one real
        token must prefill so the first sampled token has a logits row
        (partial-page sharing would need a third compiled copy program)."""
        pages = prompt_pages(prompt, self._page)
        return pages[: (len(prompt) - 1) // self._page]

    def _usable_cached_len(self, prompt_len: int, depth: int) -> int:
        """Cached tokens actually usable at warmth ``depth`` (matched
        blocks): stepped down page-by-page until the right-padded
        uncached suffix fits ``max_len`` — a warm start must never push
        the final chunk's padded write past the table (terminates at 0,
        which :meth:`check_fits` already guaranteed fits)."""
        cached = min(
            depth * self._page,
            (prompt_len - 1) // self._page * self._page,
        )
        while cached > 0 and (
            cached + self.padded_suffix(prompt_len, cached) > self.max_len
        ):
            cached -= self._page
        return cached

    def peek_prefill_cost(self, prompt) -> int:
        """Padded uncached-suffix length admission WOULD pay for this
        prompt right now, without touching cache state (LRU stamps,
        counters) — the scheduler's budget estimate."""
        plen = len(prompt)
        depth = (
            self.prefix_cache.peek(self._matchable(prompt))
            if self.prefix_cache is not None else 0
        )
        return self.padded_suffix(plen, self._usable_cached_len(plen, depth))

    def admit(self, request_id: int, prompt,
              max_new_tokens: int, *,
              version: Optional[int] = None) -> Optional[tuple]:
        """Two-resource admission: claim a slot AND the request's whole
        block reservation, reusing the longest resident prefix.  Returns
        ``(slot, cached_len)`` or None (no slot / not enough blocks even
        after evicting idle residents — backpressure, nothing leaked).

        ``prompt`` must already satisfy :meth:`check_fits` together with
        ``max_new_tokens`` — the caller validated at submit.  The
        reservation covers prompt + max_new rounded up to whole pages,
        so the request can never run out of blocks mid-decode.

        ``version`` pins the slot to a live weight version (the
        scheduler's canary routing decision); None — or a version that
        stopped being live between routing and admission — falls back
        to the primary.
        """
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        plen = len(prompt)
        n_pages = -(-(plen + max_new_tokens) // self._page)
        if self.slots.free_count < 1:
            return None
        matchable = (
            self._matchable(prompt) if self.prefix_cache is not None else []
        )
        if matchable and self.fleet_cache is not None:
            self._fleet_extend(matchable)
        depth = (
            self.prefix_cache.peek(matchable) if matchable else 0
        )
        cached = self._usable_cached_len(plen, depth)
        keep = cached // self._page
        matched = (
            self.prefix_cache.match(matchable[:keep]) if keep > 0 else []
        )
        if matched:
            # Retain BEFORE any eviction below: an evicted-but-matched
            # block must stay allocated for this request.
            self.blocks.retain(matched)
        need = n_pages - len(matched)
        if need > self.blocks.free_count and self.prefix_cache is not None:
            self.prefix_cache.evict(need - self.blocks.free_count)
            self._sync_eviction_counter()
        fresh = self.blocks.alloc(need)
        if fresh is None:
            if matched:
                self.blocks.release(matched)
            return None
        if matchable:
            hits = len(matched)
            misses = len(matchable) - hits
            if hits:
                self.registry.counter(
                    reglib.SERVE_PREFIX_CACHE_HITS
                ).inc(hits)
            if misses:
                self.registry.counter(
                    reglib.SERVE_PREFIX_CACHE_MISSES
                ).inc(misses)
        blocks = matched + fresh
        slot = self.slots.alloc(request_id)
        row = np.zeros((self._bps,), np.int32)  # padding -> sentinel 0
        row[: len(blocks)] = blocks
        self._tables[slot] = row
        self._lengths[slot] = cached
        self._slot_blocks[slot] = blocks
        self._slot_cached[slot] = cached
        if version is None or version not in self._versions:
            version = self.version
        self._slot_version[slot] = version
        return slot, cached

    def release(self, slot: int) -> int:
        """Retire ``slot``: drop the request's block references (pages
        the prefix cache adopted stay resident; the rest go back on the
        free list) and clear its table row.  Returns the request id."""
        request_id = self.slots.free(slot)
        self.blocks.release(self._slot_blocks.pop(slot))
        self._slot_cached.pop(slot, None)
        self._tables[slot] = 0
        self._lengths[slot] = 0
        self._views_fresh[slot] = False
        self._slot_version.pop(slot, None)
        self._prune_versions()
        return request_id

    # -- weight-version hot-swap (continuous deployment) --------------------
    #
    # The compiled prefill/decode programs take the weight tree as
    # argument 0, which is NOT donated (only the pool / views are), so
    # rebinding the tree between dispatches swaps weights without
    # touching any buffer a program owns — and because the deploy gate
    # proved aval equality up front, the jit cache hits the existing
    # executables: compile_counts() is pinned across every swap.  All
    # mutators run on the scheduler's worker thread, so a swap can only
    # land between bursts.

    def slot_version(self, slot: int) -> int:
        """The weight version ``slot`` was admitted under."""
        return self._slot_version.get(slot, self.version)

    def params_for(self, version: int):
        return self._versions[version]

    def live_versions(self) -> tuple:
        """Versions some live structure still references (ascending)."""
        return tuple(sorted(self._versions))

    def _prune_versions(self) -> None:
        keep = {self.version}
        if self.canary_version is not None:
            keep.add(self.canary_version)
        keep.update(self._slot_version.values())
        for vid in [v for v in self._versions if v not in keep]:
            del self._versions[vid]

    def install_canary(self, version: int, params) -> None:
        """Stage a gated candidate as the canary version.  ``params``
        must already have passed the deploy gate (finite, aval-equal to
        the live tree) — this method moves it to device and makes it
        routable, nothing more."""
        if version <= self.version:
            raise ValueError(
                f"candidate version {version} is not newer than the "
                f"primary {self.version}"
            )
        if self.canary_version is not None:
            raise ValueError(
                f"canary {self.canary_version} still in flight"
            )
        # One up-front transfer: dispatching host arrays would re-ship
        # the tree to the device on every burst.  Leaves are normalised
        # against the LIVE tree's placement because jit keys on
        # committed-ness and sharding, not just avals: checkpoint
        # restores hand back device-committed arrays while boot-time
        # init params are uncommitted, and that one-bit difference
        # would retrace both programs on the first canary burst.
        def _match(live, new):
            new = jnp.asarray(new, dtype=live.dtype)
            if getattr(live, "committed", False):
                return jax.device_put(new, live.sharding)
            if getattr(new, "committed", False):
                # Host round-trip is the only way to shed committed-ness;
                # once per candidate, on weights that just came off disk.
                return jnp.asarray(np.asarray(jax.device_get(new)),
                                   dtype=live.dtype)
            return jax.device_put(new)

        self._versions[version] = jax.tree_util.tree_map(
            _match, self.params, params
        )
        self.canary_version = version
        self._deploy_active = True

    def promote_canary(self) -> int:
        """Make the canary the primary; returns the old primary version.
        The old weights stay live until the last slot pinned to them
        retires (release() prunes)."""
        if self.canary_version is None:
            raise ValueError("no canary to promote")
        old = self.version
        self.version = self.canary_version
        self.params = self._versions[self.version]
        self.canary_version = None
        self._prune_versions()
        return old

    def rollback_canary(self) -> None:
        """Withdraw the canary from routing.  Slots already pinned to it
        finish on its weights (the byte-identity contract holds for
        rolled-back traffic too); the tree is pruned when they retire."""
        if self.canary_version is None:
            raise ValueError("no canary to roll back")
        self.canary_version = None
        self._prune_versions()

    # -- KV page shipping (disaggregated prefill/decode) -------------------
    #
    # The wire unit is the pool page: export gathers a finished slot's
    # prompt pages through the SAME gather_cache/cache_pages ops the
    # prefill program uses (so the shipped bytes are exactly what a
    # dedicated slot would hold), and import scatters them into the
    # receiving pool at freshly allocated physical blocks.  Both sides
    # run eagerly — they add ZERO compiled programs to the two jitted
    # entry points, so the per-role compile pins ((1, 0) prefill /
    # (0, 1) decode) come straight from jit laziness.  Only pages below
    # the prompt length ship: positions past it are right-padding
    # garbage that is causally masked on both ends (module docstring),
    # so decode over adopted pages reduces identically to decode over
    # the pages prefill wrote in place.

    def _flatten_pages(self, node, prefix="", out=None) -> dict:
        """Pool-shaped nested dict -> ``{"a/b/c": leaf}`` in sorted key
        order, skipping counter leaves (lengths are host truth and
        travel in the bundle header, never as pool bytes)."""
        if out is None:
            out = {}
        for k in sorted(node):
            if k in kv_slots.COUNTER_LEAVES:
                continue
            path = f"{prefix}/{k}" if prefix else k
            if isinstance(node[k], dict):
                self._flatten_pages(node[k], path, out)
            else:
                out[path] = node[k]
        return out

    def _unflatten_pages(self, flat: dict) -> dict:
        """``{"a/b/c": arr}`` -> the pool's nested-dict shape (counter
        leaves omitted — :func:`~.kv_slots.scatter_pages` never reads
        them from the page tree)."""

        def walk(node, prefix):
            out = {}
            for k in node:
                if k in kv_slots.COUNTER_LEAVES:
                    continue
                path = f"{prefix}/{k}" if prefix else k
                if isinstance(node[k], dict):
                    out[k] = walk(node[k], path)
                else:
                    if path not in flat:
                        raise ValueError(
                            f"shipped pages missing pool leaf {path!r}"
                        )
                    out[k] = flat[path]
            return out

        return walk(self.pool, "")

    def _export_prompt_pages(self, slot: int, n_pages: int) -> dict:
        """The first ``n_pages`` of ``slot``'s sequence as host arrays,
        ``{path: [n_pages, page_tokens, ...]}`` — gathered through the
        slot's block table via :func:`~.kv_slots.gather_cache` then
        re-paged via :func:`~.kv_slots.cache_pages`, the exact ops the
        compiled programs move pages with."""
        view = kv_slots.gather_cache(
            self.pool, jnp.asarray(self._tables[slot]),
            int(self._lengths[slot]),
        )
        paged = kv_slots.cache_pages(view, self._page)
        return {
            path: np.asarray(leaf[:n_pages])
            for path, leaf in self._flatten_pages(paged).items()
        }

    def export_slot(self, slot: int) -> tuple:
        """Export a prefilled slot's KV for shipping: returns
        ``(prompt_len, {path: [n_pages, page_tokens, ...]})`` covering
        ``ceil(prompt_len / page_tokens)`` pages.  Call after
        ``prefill_batch`` set the slot's true length and before
        ``release`` frees its blocks."""
        plen = int(self._lengths[slot])
        if plen < 1:
            raise ValueError(f"slot {slot} has no prefilled tokens")
        n_pages = -(-plen // self._page)
        return plen, self._export_prompt_pages(slot, n_pages)

    def _scatter_shipped(self, pages: dict, block_ids) -> None:
        """Write shipped pages (``{path: [n, page_tokens, ...]}``) into
        the pool at physical ``block_ids`` — the import side of the
        wire, via :func:`~.kv_slots.scatter_pages`."""
        indices = jnp.asarray(np.asarray(block_ids, np.int32))
        self.pool = kv_slots.scatter_pages(
            self.pool, self._unflatten_pages(pages), indices
        )

    def admit_shipped(self, request_id: int, prompt_len: int,
                      max_new_tokens: int, pages: dict):
        """Decode-side admission of a shipped request: claim a slot AND
        the request's FULL fresh reservation (no prefix matching — the
        prompt's KV arrives on the wire), scatter the shipped prompt
        pages in, and mark the lane for view adoption.  Returns the
        slot, or None on backpressure (slots/blocks exhausted — nothing
        leaked, the caller requeues).  The adopted lane then decodes
        byte-identically to one the local prefill program filled: the
        gathered view is the same bytes either way."""
        plen = int(prompt_len)
        if plen < 1:
            raise ValueError("shipped prompt_len must be >= 1")
        n_pages = -(-plen // self._page)
        for path, arr in pages.items():
            if arr.shape[0] != n_pages or arr.shape[1] != self._page:
                raise ValueError(
                    f"shipped leaf {path!r} shape {arr.shape} does not "
                    f"cover {n_pages} pages of {self._page} tokens"
                )
        n_need = -(-(plen + max_new_tokens) // self._page)
        if self.slots.free_count < 1:
            return None
        if n_need > self.blocks.free_count and self.prefix_cache is not None:
            self.prefix_cache.evict(n_need - self.blocks.free_count)
            self._sync_eviction_counter()
        fresh = self.blocks.alloc(n_need)
        if fresh is None:
            return None
        self._scatter_shipped(pages, fresh[:n_pages])
        slot = self.slots.alloc(request_id)
        row = np.zeros((self._bps,), np.int32)
        row[: len(fresh)] = fresh
        self._tables[slot] = row
        self._lengths[slot] = plen
        self._slot_blocks[slot] = fresh
        self._slot_cached[slot] = 0
        self._views_fresh[slot] = True
        # Shipped requests decode on the primary at adoption time; the
        # pin keeps them there across any later swap (byte-identity).
        self._slot_version[slot] = self.version
        return slot

    def _fleet_extend(self, matchable: list) -> None:
        """Pull pages the local trie misses from the fleet index: adopt
        the longest advertised extension into freshly allocated blocks
        and insert it into the local trie, so the normal match path
        reuses fleet pages exactly like locally prefilled ones.  Counts
        ``serve/fleet_prefix_{hits,misses}`` block-granularly over the
        consulted tail.  Failure to adopt (no block headroom) is a
        miss, never an error."""
        depth = self.prefix_cache.peek(matchable)
        if depth >= len(matchable):
            return
        found = self.fleet_cache.lookup(matchable)
        n_new = len(found) - depth
        misses = len(matchable) - max(depth, len(found))
        if n_new > 0:
            if n_new > self.blocks.free_count:
                self.prefix_cache.evict(n_new - self.blocks.free_count)
                self._sync_eviction_counter()
            fresh = self.blocks.alloc(n_new)
            if fresh is None:
                misses += n_new
                n_new = 0
            else:
                stacked = {
                    path: np.stack(
                        [np.asarray(lv[path]) for lv in found[depth:]]
                    )
                    for path in found[depth]
                }
                self._scatter_shipped(stacked, fresh)
                # Chain blocks for the trie walk: the already-resident
                # prefix keeps its own blocks (insert leaves existing
                # nodes untouched), the extension adopts the fresh
                # ones; our temporary alloc reference is dropped once
                # the cache holds its own.
                chain = (
                    self.prefix_cache.match(matchable[:depth]) + fresh
                )
                self.prefix_cache.insert(matchable[:len(found)], chain)
                self.blocks.release(fresh)
                self._sync_eviction_counter()
        if n_new > 0:
            self.registry.counter(
                reglib.SERVE_FLEET_PREFIX_HITS
            ).inc(n_new)
        if misses > 0:
            self.registry.counter(
                reglib.SERVE_FLEET_PREFIX_MISSES
            ).inc(misses)

    def _fleet_advertise(self, slot: int, pages: list) -> None:
        """Advertise a freshly prefilled prompt's shareable pages to
        the fleet index (publish-if-absent; skipped wholesale when
        every digest is already advertised, so steady-state repeat
        traffic exports nothing)."""
        if not pages or not self.fleet_cache.any_missing(pages):
            return
        stacked = self._export_prompt_pages(slot, len(pages))
        self.fleet_cache.advertise(
            pages,
            [
                {path: arr[i] for path, arr in stacked.items()}
                for i in range(len(pages))
            ],
        )

    def _sync_eviction_counter(self) -> None:
        delta = (
            self.prefix_cache.evictions - self._evictions_seen
            if self.prefix_cache is not None else 0
        )
        if delta:
            self.registry.counter(
                reglib.SERVE_PREFIX_CACHE_EVICTIONS
            ).inc(delta)
            self._evictions_seen = self.prefix_cache.evictions

    # -- pool telemetry -----------------------------------------------------

    @property
    def blocks_free(self) -> int:
        return self.blocks.free_count

    @property
    def blocks_resident(self) -> int:
        return (
            self.prefix_cache.resident_count
            if self.prefix_cache is not None else 0
        )

    def fragmentation(self) -> float:
        """Internal fragmentation of active reservations: the fraction
        of block-granular token capacity reserved by in-flight requests
        that holds no live token yet (0.0 when idle).  High values mean
        ``kv_page_tokens`` is coarse relative to typical lengths."""
        reserved = sum(len(b) for b in self._slot_blocks.values())
        if reserved == 0:
            return 0.0
        live = sum(int(self._lengths[s]) for s in self._slot_blocks)
        return 1.0 - live / (reserved * self._page)

    # -- the two device programs ------------------------------------------

    def _prefill_fn(self, params, pool, tables, tokens, start, keydata,
                    temperature, top_k, top_p, last):
        """One prompt chunk per lane, ``prefill_lanes`` lanes per
        dispatch.  Per lane: ``tokens`` row is ``[chunk]`` right-padded,
        ``start`` the real position before it, ``last`` the chunk-local
        index of the last real token (its logits seed the first
        generated token on the final chunk — the caller ignores the
        sample for earlier chunks and for inert lanes).  Every lane's
        pages scatter back in one flattened write; shared and sentinel
        blocks may repeat across lanes, carrying identical (resp.
        unreachable) values — see :mod:`.kv_slots`."""

        def one(table, toks, s, kd, t, k, p, li):
            cache = kv_slots.gather_cache(pool, table, s)
            (logits, _), mutated = self._decode_model.apply(
                {"params": params, "cache": cache}, toks[None],
                train=False, mutable=["cache"],
            )
            row = logits[0].astype(jnp.float32)[li]
            tok = sample_dynamic(row, kd, t, k, p, jnp.int32)
            return kv_slots.cache_pages(mutated["cache"], self._page), tok

        pages, toks = jax.vmap(one)(
            tables, tokens, start, keydata, temperature, top_k, top_p,
            last,
        )
        flat = jax.tree_util.tree_map(
            lambda a: a.reshape((-1,) + a.shape[2:]), pages
        )
        pool = kv_slots.scatter_pages(pool, flat, tables.reshape(-1))
        return pool, toks

    def _decode_fn(self, params, views, pool, refresh, tables, lengths,
                   tokens, drafts, keydata, temperature, top_k, top_p):
        """One batched decode dispatch over the persistent decode
        working set (``views``, donated in and out): lanes the host
        flagged in ``refresh`` first re-adopt their view from the pool
        — a gather through their block table, paid once per admission,
        not per dispatch (ONE ``lax.cond`` over the whole working set,
        so dispatches with no refresh execute the identity branch and
        copy nothing) — then one of two bodies, selected by the STATIC
        width of ``drafts`` (``[S, D]`` int32; D is 0 or the engine's
        construction-time ``spec_tokens``, so the selection is a shape
        fact, never traffic):

        **D == 0 (burst decode)** — the unmodified B=1 single-token
        apply (vmapped over lanes) advances every view ``decode_burst``
        tokens by ``lax.scan``, each lane's sample feeding back as its
        next input token: exactly ``generate()``'s recurrence, and
        exactly the slotted engine's decode program over the same
        bytes, so paging, burst length, and adoption timing cannot
        move a bit.  ``keydata`` is ``[S, K, *key]``; returns the
        ``[K, S]`` token matrix.  Overrun lanes clamp their writes
        inside their own view and the caller discards their samples;
        free slots ride along as inert lanes.

        **D > 0 (speculative verify)** — the scan's carried next-input
        token is replaced by the drafted window: each lane applies the
        model ONCE over ``[last_token, d_1 .. d_D]`` (width
        ``W = D + 1`` — the multi-token decode apply prefill already
        uses), computing target logits at every drafted position in a
        single forward pass, and samples every position with its own
        ``key_schedule`` key via the same :func:`sample_dynamic`.  Row
        ``i`` is the token solo decoding would emit next IF the first
        ``i`` drafts matched; the host accepts the matched prefix and
        rolls the rest back (:meth:`decode_step`), so byte-identity is
        definitional at any acceptance rate.  Returns the ``[S, W]``
        candidate matrix.  Draft padding (-1 = no proposal) clamps to
        token 0 for the embedding gather; those positions' samples are
        never accepted host-side and their K/V writes land past the
        rolled-back length, overwritten by the next window before any
        query row can attend to them.

        The pool is READ-ONLY in both bodies; generated K/V lives only
        in the views (nothing ever reads a suffix page from the pool —
        the prefix cache shares prompt pages, which prefill wrote), so
        rejected drafts can never corrupt a shared or copy-on-write
        prefix page."""
        views = lax.cond(
            jnp.any(refresh),
            lambda v: kv_slots.adopt_lanes(v, pool, tables, refresh),
            lambda v: v,
            views,
        )
        caches = kv_slots.set_counters(views, lengths)

        if drafts.shape[1] > 0:
            def one_verify(cache, tok, dr, kd, t, k, p):
                window = jnp.concatenate(
                    [tok[None], jnp.maximum(dr, 0)]
                )[None]  # [1, W]
                (logits, _), mutated = self._decode_model.apply(
                    {"params": params, "cache": cache}, window,
                    train=False, mutable=["cache"],
                )
                rows = logits[0].astype(jnp.float32)  # [W, V]
                # Unrolled per-position sampling (W is static and
                # small): each row goes through the exact
                # sample_dynamic computation the burst scan runs, so
                # the sampled bits match solo decoding's per position.
                cand = jnp.stack([
                    sample_dynamic(rows[i], kd[i], t, k, p, jnp.int32)
                    for i in range(rows.shape[0])
                ])
                return mutated["cache"], cand

            caches, out = jax.vmap(one_verify)(
                caches, tokens, drafts, keydata, temperature, top_k,
                top_p,
            )
            return kv_slots.placeholder_counters(views, caches), out

        def burst_step(carry, kd_t):
            caches_t, toks = carry

            def one(cache, tok, kd, t, k, p):
                (logits, _), mutated = self._decode_model.apply(
                    {"params": params, "cache": cache}, tok[None, None],
                    train=False, mutable=["cache"],
                )
                row = logits[0, -1].astype(jnp.float32)
                nxt = sample_dynamic(row, kd, t, k, p, jnp.int32)
                return mutated["cache"], nxt

            caches_t, nxt = jax.vmap(one)(
                caches_t, toks, kd_t, temperature, top_k, top_p
            )
            return (caches_t, nxt), nxt

        (caches, _), out = lax.scan(
            burst_step, (caches, tokens), jnp.swapaxes(keydata, 0, 1)
        )
        return kv_slots.placeholder_counters(views, caches), out

    # -- host-facing ops ---------------------------------------------------

    def prefill(self, slot: int, prompt: np.ndarray, keydata: np.ndarray,
                temperature: float, top_k: int, top_p: float) -> int:
        """Run one request's uncached prompt suffix into ``slot``;
        returns the first generated token.  Single-request convenience
        over :meth:`prefill_batch`."""
        return self.prefill_batch(
            [(slot, prompt, keydata, temperature, top_k, top_p)]
        )[slot]

    def prefill_batch(self, items: list) -> dict:
        """Prefill a burst of admitted requests, ``prefill_lanes`` at a
        time per dispatch of the ONE prefill program.  ``items`` is a
        list of ``(slot, prompt, keydata0, temperature, top_k, top_p)``
        (``keydata0`` = key 0 of the request's schedule, matching
        ``generate()``'s seeding of the first token from the prompt's
        last logits).  Each lane starts at its admitted cached length —
        resident prefix pages are never re-prefilled.  Lanes with
        shorter suffixes go inert once done (sentinel tables).  After
        the burst completes, every prompt's shareable pages are inserted
        into the prefix cache — never earlier, so a same-burst twin
        cannot match blocks that are still being filled.  Returns
        ``{slot: first_token}``."""
        out = {}
        # Partition by the slots' pinned weight versions: each group
        # dispatches the SAME compiled program with its own weight tree
        # (aval-equal by the deploy gate, so no version ever retraces).
        # With no deploy attached every slot pins the boot version and
        # this degenerates to the single-group PR 12 path.
        byver: dict = {}
        for item in items:
            byver.setdefault(self.slot_version(item[0]), []).append(item)
        with self.registry.span(reglib.SERVE_PREFILL):
            for vid in sorted(byver):
                self._prefill_group(
                    self._versions.get(vid, self.params), byver[vid], out
                )
        return out

    def _prefill_group(self, vparams, items: list, out: dict) -> None:
        """Prefill one weight-version's items (the PR 12 group loop,
        dispatching with that version's tree)."""
        lanes, c = self.prefill_lanes, self.prefill_chunk
        for g in range(0, len(items), lanes):
            plans = []
            for slot, prompt, kd0, t, k, p in items[g:g + lanes]:
                prompt = np.asarray(prompt, np.int32).reshape(-1)
                lo0 = self._slot_cached.get(slot, 0)
                bounds = [
                    (lo, min(lo + c, len(prompt)))
                    for lo in range(lo0, len(prompt), c)
                ]
                plans.append((slot, prompt, kd0, t, k, p, bounds))
            for w in range(max(len(pl[6]) for pl in plans)):
                tables = np.zeros((lanes, self._bps), np.int32)
                tokens = np.zeros((lanes, c), np.int32)
                starts = np.zeros((lanes,), np.int32)
                keydata = np.zeros(
                    (lanes,) + self._key_shape, self._key_dtype
                )
                temperature = np.zeros((lanes,), np.float32)
                top_k = np.zeros((lanes,), np.int32)
                top_p = np.ones((lanes,), np.float32)
                last = np.zeros((lanes,), np.int32)
                for i, (slot, prompt, kd0, t, k, p, bounds) in (
                    enumerate(plans)
                ):
                    if w >= len(bounds):
                        continue  # inert lane
                    lo, hi = bounds[w]
                    tables[i] = self._tables[slot]
                    tokens[i, : hi - lo] = prompt[lo:hi]
                    starts[i] = lo
                    keydata[i] = np.asarray(
                        kd0, self._key_dtype
                    ).reshape(self._key_shape)
                    temperature[i] = t
                    top_k[i] = k
                    top_p[i] = p
                    last[i] = hi - lo - 1
                self.pool, toks = self._prefill_j(
                    vparams, self.pool, jnp.asarray(tables),
                    jnp.asarray(tokens), jnp.asarray(starts),
                    jnp.asarray(keydata), jnp.asarray(temperature),
                    jnp.asarray(top_k), jnp.asarray(top_p),
                    jnp.asarray(last),
                )
                toks = np.asarray(toks)
                for i, (slot, *_rest, bounds) in enumerate(plans):
                    if w == len(bounds) - 1:
                        out[slot] = int(toks[i])
            for slot, prompt, *_rest in plans:
                self._lengths[slot] = len(prompt)
                self._views_fresh[slot] = True
                if self.prefix_cache is not None:
                    pages = self._matchable(prompt)
                    if pages:
                        self.prefix_cache.insert(
                            pages,
                            [int(b) for b in
                             self._tables[slot][:len(pages)]],
                        )
                        self._sync_eviction_counter()
                        if self.fleet_cache is not None:
                            self._fleet_advertise(slot, pages)

    def decode_step(self, lanes: dict) -> dict:
        """One batched decode dispatch.  ``lanes`` maps slot ->
        ``(last_token, keydata_rows, temperature, top_k, top_p)`` — or,
        with speculation on, the same plus a sixth ``draft_row``
        element (``[spec_tokens]`` int32, -1 = no proposal; see
        :mod:`.drafter`) — for every ACTIVE slot.  ``keydata_rows`` is
        ``[r, *key]``, the lane's remaining key schedule up to the
        dispatch width (a lane with fewer tokens left passes only what
        remains; the zero-padded tail samples garbage the caller must
        discard — such a lane finishes inside this dispatch, so its
        slot is retired and the overrun never reaches a live request).

        Routing is host-side and data-driven: when ``spec_tokens > 0``
        AND at least one lane proposed a draft token, the dispatch is a
        speculative VERIFY (one width-``spec_tokens+1`` apply per lane;
        each lane emits its accepted draft prefix plus the target's own
        correction token — between 1 and ``spec_tokens + 1`` tokens —
        and its length counter rolls back over the rejected tail via
        :func:`~.kv_slots.rollback_length`); otherwise it is the plain
        ``decode_burst``-token burst, byte-for-byte the PR 12 dispatch
        — so zero-match traffic pays the drafter's host lookups and
        nothing else.  Returns ``{slot: [token, ...]}``.  Inactive
        slots run as inert sentinel lanes — the program shape never
        depends on how many requests are live.

        With a canary in flight, lanes pinned to different weight
        versions split into per-version dispatches of the SAME compiled
        program (aval-equal trees — no retrace).  Lanes outside the
        dispatching version ride along as riders: real table row and
        real length so their garbage writes land at positions at or
        past their write head (overwritten by their own version's
        dispatch before any read — the module's right-padding soundness
        argument), outputs discarded, host lengths untouched."""
        byver: dict = {}
        for slot in lanes:
            byver.setdefault(self.slot_version(slot), []).append(slot)
        out: dict = {}
        for vid in sorted(byver):
            group = {s: lanes[s] for s in byver[vid]}
            extra = tuple(s for s in lanes if s not in group)
            verify = False
            if self.spec_tokens:
                for lane in group.values():
                    if len(lane) > 5 and lane[5] is not None and (
                        np.asarray(lane[5]) >= 0
                    ).any():
                        verify = True
                        break
            if verify:
                out.update(self._verify_dispatch(group, vid, extra))
            else:
                out.update(self._burst_dispatch(group, vid, extra))
        return out

    def _burst_dispatch(
        self, lanes: dict, vid: Optional[int] = None,
        extra_slots: tuple = (),
    ) -> dict:
        s, k = self.max_slots, self.decode_burst
        tables = np.zeros((s, self._bps), np.int32)
        lengths = np.zeros((s,), np.int32)
        tokens = np.zeros((s,), np.int32)
        drafts = np.zeros((s, 0), np.int32)  # static width 0: burst body
        keydata = np.zeros((s, k) + self._key_shape, self._key_dtype)
        temperature = np.zeros((s,), np.float32)
        top_k = np.zeros((s,), np.int32)
        top_p = np.ones((s,), np.float32)
        refresh = np.zeros((s,), bool)
        for slot, lane in lanes.items():
            tok, kd, t, tk, p = lane[:5]
            tables[slot] = self._tables[slot]
            lengths[slot] = self._lengths[slot]
            tokens[slot] = tok
            kd = np.asarray(kd, self._key_dtype).reshape(
                (-1,) + self._key_shape
            )[:k]
            keydata[slot, : kd.shape[0]] = kd
            temperature[slot] = t
            top_k[slot] = tk
            top_p[slot] = p
            # Adopt only lanes whose pool bytes are newer than their
            # view AND whose real table row is on this dispatch (a
            # fresh slot not decoded yet keeps its flag for later).
            if self._views_fresh[slot]:
                refresh[slot] = True
        for slot in extra_slots:
            # Rider lanes (pinned to another weight version): real row
            # + real length keep their garbage writes at or past the
            # write head; refresh stays False (re-adopting a decoded
            # lane from the pool would destroy its decoded-suffix K/V).
            tables[slot] = self._tables[slot]
            lengths[slot] = self._lengths[slot]
        vparams = (
            self._versions.get(vid, self.params)
            if vid is not None else self.params
        )
        # Explicit timing, not registry.span: the dispatch loop stays
        # free of contextmanager enters/exits, and the trace event gets
        # dispatch-kind args the generic span can't carry.
        t0 = time.perf_counter()
        self._views, nxt = self._decode_j(
            vparams, self._views, self.pool,
            jnp.asarray(refresh), jnp.asarray(tables),
            jnp.asarray(lengths), jnp.asarray(tokens),
            jnp.asarray(drafts), jnp.asarray(keydata),
            jnp.asarray(temperature), jnp.asarray(top_k),
            jnp.asarray(top_p),
        )
        nxt = np.asarray(nxt)  # [K, S]
        dt = time.perf_counter() - t0
        self.registry.timer(reglib.SERVE_DECODE).record(dt)
        if self.registry.trace.enabled:
            self.registry.trace.complete(
                reglib.SERVE_DECODE, dt, ts_mono=t0,
                args={"kind": "burst", "lanes": len(lanes), "width": k},
            )
        self._views_fresh[refresh] = False
        for slot in lanes:
            self._lengths[slot] += k
        return {
            slot: [int(nxt[i, slot]) for i in range(k)] for slot in lanes
        }

    def _verify_dispatch(
        self, lanes: dict, vid: Optional[int] = None,
        extra_slots: tuple = (),
    ) -> dict:
        """Speculative verify: one width-``spec_tokens+1`` apply per
        lane through the one decode entry point, then host-side
        accepted-prefix truncation + length rollback.  A lane's
        emitted tokens are ALL target samples (the accepted candidates
        equal the matched drafts by the accept rule; the final token is
        the target's correction) — drafts steer which positions get
        verified, never what is emitted, which is why byte-identity to
        solo ``generate()`` holds at any acceptance rate."""
        s, spec = self.max_slots, self.spec_tokens
        w = spec + 1
        tables = np.zeros((s, self._bps), np.int32)
        lengths = np.zeros((s,), np.int32)
        tokens = np.zeros((s,), np.int32)
        drafts = np.full((s, spec), -1, np.int32)
        keydata = np.zeros((s, w) + self._key_shape, self._key_dtype)
        temperature = np.zeros((s,), np.float32)
        top_k = np.zeros((s,), np.int32)
        top_p = np.ones((s,), np.float32)
        refresh = np.zeros((s,), bool)
        for slot, lane in lanes.items():
            tok, kd, t, tk, p = lane[:5]
            tables[slot] = self._tables[slot]
            lengths[slot] = self._lengths[slot]
            tokens[slot] = tok
            kd = np.asarray(kd, self._key_dtype).reshape(
                (-1,) + self._key_shape
            )[:w]
            keydata[slot, : kd.shape[0]] = kd
            temperature[slot] = t
            top_k[slot] = tk
            top_p[slot] = p
            if len(lane) > 5 and lane[5] is not None:
                dr = np.asarray(lane[5], np.int32).reshape(-1)[:spec]
                drafts[slot, : dr.shape[0]] = dr
            if self._views_fresh[slot]:
                refresh[slot] = True
        for slot in extra_slots:
            # Rider lanes: see _burst_dispatch — real row + length, no
            # refresh, no draft (row stays -1), output discarded.
            tables[slot] = self._tables[slot]
            lengths[slot] = self._lengths[slot]
        vparams = (
            self._versions.get(vid, self.params)
            if vid is not None else self.params
        )
        t0 = time.perf_counter()
        self._views, cand = self._decode_j(
            vparams, self._views, self.pool,
            jnp.asarray(refresh), jnp.asarray(tables),
            jnp.asarray(lengths), jnp.asarray(tokens),
            jnp.asarray(drafts), jnp.asarray(keydata),
            jnp.asarray(temperature), jnp.asarray(top_k),
            jnp.asarray(top_p),
        )
        cand = np.asarray(cand)  # [S, W]
        dt = time.perf_counter() - t0
        self.registry.timer(reglib.SERVE_DECODE).record(dt)
        if self.registry.trace.enabled:
            self.registry.trace.complete(
                reglib.SERVE_DECODE, dt, ts_mono=t0,
                args={"kind": "verify", "lanes": len(lanes), "width": w},
            )
        self._views_fresh[refresh] = False
        out: dict = {}
        drafted = accepted = emitted = 0
        for slot in lanes:
            row = cand[slot]
            dvec = drafts[slot]
            # Accept rule: draft i is accepted iff the target's own
            # sample at its position equals it; emit the accepted
            # prefix plus the first mismatch's target sample.
            m = 1
            while m <= spec and dvec[m - 1] >= 0 and (
                int(dvec[m - 1]) == int(row[m - 1])
            ):
                m += 1
            drafted += int((dvec >= 0).sum())
            accepted += m - 1
            emitted += m
            self._lengths[slot] = kv_slots.rollback_length(
                int(self._lengths[slot]), w, m
            )
            out[slot] = [int(row[i]) for i in range(m)]
        self.registry.counter(reglib.SERVE_SPEC_DRAFTED).inc(drafted)
        self.registry.counter(reglib.SERVE_SPEC_ACCEPTED).inc(accepted)
        if drafted:
            self.registry.timer(reglib.SERVE_SPEC_ACCEPTANCE_RATE).record(
                accepted / drafted
            )
            if self._deploy_active and vid is not None:
                # Per-version acceptance split (dispatches are already
                # version-partitioned, so the group rate IS the
                # version's rate).
                self.registry.timer(
                    f"{reglib.SERVE_VERSION_ACCEPTANCE}/{vid}"
                ).record(accepted / drafted)
        self.registry.timer(
            reglib.SERVE_SPEC_TOKENS_PER_DISPATCH
        ).record(emitted / len(lanes))
        return out

    def fsck(self) -> list:
        """Fsck-style arena audit (:func:`~.kv_slots.check_arena`)
        over the live slot tables, rolled-back lengths, block
        ownership, and the prefix trie's residency ledger; returns
        violation strings (empty = consistent).  Cheap enough to run
        after every scheduler iteration in tests."""
        return kv_slots.check_arena(
            self.blocks, self._tables, self._lengths, self._slot_blocks,
            self._page,
            resident_blocks=(
                self.prefix_cache.resident_blocks()
                if self.prefix_cache is not None else ()
            ),
        )

    def compile_counts(self) -> tuple[int, int]:
        """(prefill, decode) compiled-program counts — the
        shape-stability invariant tests pin.  With ``spec_tokens == 0``
        the pin is ``(1, 1)`` exactly as in PR 12.  With speculation on
        the decode entry point traces a SECOND instance — the
        width-``spec_tokens+1`` verify body, selected by the static
        draft-operand width — so a spec-on engine steady-states at
        ``(1, 2)``: a deliberate, documented pin update (one extra
        program per engine lifetime, fixed at construction like
        ``decode_burst``), never a per-traffic recompile."""
        return (
            int(self._prefill_j._cache_size()),
            int(self._decode_j._cache_size()),
        )
