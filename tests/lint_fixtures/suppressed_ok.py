"""Suppressions: one used (silences a real finding), one unused."""
import time


def next_cursor(cursor):
    stamp = time.time()  # dtmlint: disable=determinism-hazard
    return cursor + stamp


# dtmlint: disable=int32-wire
def nothing():
    return 0
