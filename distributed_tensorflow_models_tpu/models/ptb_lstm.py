"""PTB LSTM language model — truncated-BPTT on TPU via ``lax.scan``.

Reference component R8 (SURVEY.md §2.1): the TF PTB tutorial — a 2-layer
LSTM LM (Zaremba et al. 2014) with truncated BPTT over ``num_steps`` tokens,
dropout between layers, gradients clipped by global norm, SGD with staged LR
decay, and small/medium/large configs.  Critically, the reference threads
the final LSTM state of each segment into the next (SURVEY.md §7.4.5) — here
the carry is an explicit input/output of ``__call__`` so the train loop can
keep it in the (sharded) train state.

TPU-first: the time unroll is ``nn.scan`` (compiled ``lax.scan``), not a
Python loop — one compiled step regardless of ``num_steps``; each scan step
is a batched matmul hitting the MXU.  The carry is batch-sharded along the
``data`` mesh axis like any activation, which is exactly the "sharded scan
state" design SURVEY.md §2.4 calls for.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_tensorflow_models_tpu.models import register

# Per-layer carry: (c, h) tuples, batch-major.
Carry = Sequence[tuple[jax.Array, jax.Array]]


class _StackedCell(nn.Module):
    """One time step through the layer stack, scanned over time."""

    hidden_size: int
    num_layers: int
    dropout_rate: float
    train: bool

    @nn.compact
    def __call__(self, carry, x):
        new_carry = []
        h = x
        for i in range(self.num_layers):
            cell = nn.OptimizedLSTMCell(self.hidden_size, name=f"lstm_{i}")
            c_i, h = cell(tuple(carry[i]), h)
            new_carry.append(c_i)
            if self.dropout_rate:
                h = nn.Dropout(
                    self.dropout_rate, deterministic=not self.train
                )(h)
        return tuple(new_carry), h


class PTBLSTM(nn.Module):
    """Input ``tokens [B, T]`` int32 + carry; returns ``(logits [B, T, V],
    new_carry)``."""

    vocab_size: int = 10000
    hidden_size: int = 650  # "medium" config
    num_layers: int = 2
    dropout_rate: float = 0.5
    dtype: jnp.dtype = jnp.float32

    def initial_carry(self, batch_size: int) -> Carry:
        zeros = lambda: jnp.zeros(
            (batch_size, self.hidden_size), self.dtype
        )
        return tuple(
            (zeros(), zeros()) for _ in range(self.num_layers)
        )

    @nn.compact
    def __call__(self, tokens, carry: Carry | None = None,
                 train: bool = False):
        if carry is None:
            carry = self.initial_carry(tokens.shape[0])
        x = nn.Embed(
            self.vocab_size, self.hidden_size, dtype=self.dtype,
            name="embedding",
        )(tokens)
        if self.dropout_rate:
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)

        scan = nn.scan(
            _StackedCell,
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": True},
            in_axes=1,
            out_axes=1,
        )
        carry, outputs = scan(
            self.hidden_size,
            self.num_layers,
            self.dropout_rate,
            train,
            name="stack",
        )(tuple(tuple(c) for c in carry), x)
        logits = nn.Dense(
            self.vocab_size, dtype=jnp.float32, name="head"
        )(outputs)
        return logits, carry


# The three classic Zaremba configs the reference exposes (SURVEY.md §2.1 R8).
PTB_CONFIGS = {
    "small": dict(hidden_size=200, dropout_rate=0.0),
    "medium": dict(hidden_size=650, dropout_rate=0.5),
    "large": dict(hidden_size=1500, dropout_rate=0.65),
}


@register("ptb_lstm")
def build_ptb_lstm(config: str = "medium", **kwargs) -> PTBLSTM:
    base = dict(PTB_CONFIGS[config])
    base.update(kwargs)
    return PTBLSTM(**base)
