"""Data-pipeline tests: container format, Example codec, augmentation,
dataset iteration + mid-epoch resume, host prefetch pipeline.

TF 2.21 (installed) is used as the *oracle* for wire-format compatibility —
SURVEY.md §4.5's parity-harness strategy.
"""

import numpy as np
import pytest

from distributed_tensorflow_models_tpu.data import (
    augment,
    datasets,
    example_proto,
    pipeline,
    tfrecord,
)


# --------------------------------------------------------------------------
# TFRecord container
# --------------------------------------------------------------------------


def test_tfrecord_roundtrip(tmp_path):
    path = tmp_path / "a.tfrecord"
    payloads = [b"hello", b"", b"x" * 10_000, bytes(range(256))]
    assert tfrecord.write_records(path, payloads) == 4
    assert list(tfrecord.read_records(path)) == payloads


def test_tfrecord_crc_detects_corruption(tmp_path):
    path = tmp_path / "a.tfrecord"
    tfrecord.write_records(path, [b"payload-data"])
    raw = bytearray(path.read_bytes())
    raw[14] ^= 0xFF  # flip a payload byte
    path.write_bytes(bytes(raw))
    with pytest.raises(tfrecord.CorruptRecordError):
        list(tfrecord.read_records(path))


def test_tfrecord_matches_tf_oracle(tmp_path):
    tf = pytest.importorskip("tensorflow")
    path = str(tmp_path / "oracle.tfrecord")
    payloads = [b"first", b"second" * 100]
    with tf.io.TFRecordWriter(path) as w:
        for p in payloads:
            w.write(p)
    assert list(tfrecord.read_records(path)) == payloads
    # And TF can read ours.
    ours = str(tmp_path / "ours.tfrecord")
    tfrecord.write_records(ours, payloads)
    got = [bytes(r.numpy()) for r in tf.data.TFRecordDataset(ours)]
    assert got == payloads


def test_crc32c_known_values():
    # RFC 3720 test vector: 32 zero bytes -> 0x8a9136aa.
    assert tfrecord.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert tfrecord.crc32c(b"123456789") == 0xE3069283


def test_sharded_iterator_resume(tmp_path):
    paths = []
    for s in range(3):
        p = str(tmp_path / f"s{s}.tfrecord")
        tfrecord.write_records(
            p, [f"{s}-{i}".encode() for i in range(5)]
        )
        paths.append(p)
    it = tfrecord.ShardedRecordIterator(paths, seed=7)
    stream = iter(it)
    first = [next(stream) for _ in range(8)]
    state = it.get_state()

    it2 = tfrecord.ShardedRecordIterator(paths, seed=7)
    it2.set_state(state)
    rest = [next(iter(it2)) for _ in range(7)]

    it3 = tfrecord.ShardedRecordIterator(paths, seed=7)
    full = [next(iter(it3)) for _ in range(15)]
    assert first + rest == full


# --------------------------------------------------------------------------
# Example proto codec
# --------------------------------------------------------------------------


def test_example_roundtrip_self():
    feats = {
        "image/encoded": [b"\x00\x01jpegdata"],
        "image/class/label": [42],
        "bbox": [0.1, 0.2, 0.9, 0.8],
    }
    parsed = example_proto.parse_example(example_proto.build_example(feats))
    assert parsed["image/encoded"] == [b"\x00\x01jpegdata"]
    assert parsed["image/class/label"] == [42]
    np.testing.assert_allclose(parsed["bbox"], feats["bbox"], rtol=1e-6)


def test_example_matches_tf_oracle():
    tf = pytest.importorskip("tensorflow")
    ex = tf.train.Example(
        features=tf.train.Features(
            feature={
                "image/encoded": tf.train.Feature(
                    bytes_list=tf.train.BytesList(value=[b"rawbytes"])
                ),
                "image/class/label": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[7, -3])
                ),
                "w": tf.train.Feature(
                    float_list=tf.train.FloatList(value=[1.5, -2.25])
                ),
            }
        )
    )
    parsed = example_proto.parse_example(ex.SerializeToString())
    assert parsed["image/encoded"] == [b"rawbytes"]
    assert parsed["image/class/label"] == [7, -3]
    np.testing.assert_allclose(parsed["w"], [1.5, -2.25])

    # Reverse direction: TF parses what we build.
    ours = example_proto.build_example(
        {"label": [5], "name": [b"x"], "f": [0.5]}
    )
    parsed_tf = tf.io.parse_single_example(
        ours,
        {
            "label": tf.io.FixedLenFeature([], tf.int64),
            "name": tf.io.FixedLenFeature([], tf.string),
            "f": tf.io.FixedLenFeature([], tf.float32),
        },
    )
    assert int(parsed_tf["label"]) == 5
    assert bytes(parsed_tf["name"].numpy()) == b"x"
    assert float(parsed_tf["f"]) == 0.5


# --------------------------------------------------------------------------
# Augmentation
# --------------------------------------------------------------------------


def test_per_image_standardization_matches_tf():
    tf = pytest.importorskip("tensorflow")
    rng = np.random.RandomState(0)
    img = rng.rand(16, 16, 3).astype(np.float32)
    ours = augment.per_image_standardization(img)
    theirs = tf.image.per_image_standardization(img).numpy()
    np.testing.assert_allclose(ours, theirs, rtol=1e-4, atol=1e-5)
    assert abs(ours.mean()) < 1e-4
    # JAX batched variant agrees.
    jax_out = np.asarray(
        augment.jax_per_image_standardization(img[None])[0]
    )
    np.testing.assert_allclose(jax_out, ours, rtol=1e-4, atol=1e-5)


def test_cifar_train_preprocess_shapes_and_determinism():
    img = np.random.RandomState(1).rand(32, 32, 3).astype(np.float32)
    a = augment.preprocess_cifar_train(img, np.random.default_rng(3))
    b = augment.preprocess_cifar_train(img, np.random.default_rng(3))
    c = augment.preprocess_cifar_train(img, np.random.default_rng(4))
    assert a.shape == (32, 32, 3)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


def test_distorted_bbox_crop_properties():
    rng = np.random.default_rng(0)
    areas = []
    for _ in range(50):
        top, left, h, w = augment.sample_distorted_bounding_box((480, 640), rng)
        assert 0 <= top <= 480 - h and 0 <= left <= 640 - w
        assert 1 <= h <= 480 and 1 <= w <= 640
        areas.append(h * w / (480 * 640))
        assert 0.6 <= (w / h) <= 1.45  # aspect within sampled range + rounding
    assert min(areas) < 0.4 and max(areas) > 0.5  # spans the area range


def test_imagenet_train_preprocess():
    img = (np.random.RandomState(2).rand(64, 80, 3) * 255).astype(np.uint8)
    out = augment.preprocess_imagenet_train(
        img, np.random.default_rng(1), size=32
    )
    assert out.shape == (32, 32, 3)
    assert out.min() >= -1.0 - 1e-6 and out.max() <= 1.0 + 1e-6


def test_imagenet_eval_preprocess_central_crop():
    img = (np.random.RandomState(2).rand(100, 100, 3) * 255).astype(np.uint8)
    out = augment.preprocess_imagenet_eval(img, size=24)
    assert out.shape == (24, 24, 3)


def test_jpeg_roundtrip_decode():
    yy, xx = np.mgrid[0:40, 0:40]
    img = np.stack([yy * 6, xx * 6, (yy + xx) * 3], axis=-1).astype(np.uint8)
    decoded = augment.decode_jpeg(augment.encode_jpeg(img, quality=95))
    assert decoded.shape == (40, 40, 3)
    assert np.abs(decoded.astype(int) - img.astype(int)).mean() < 8


def test_jax_random_crop_with_pad():
    import jax

    imgs = np.random.RandomState(0).rand(4, 8, 8, 3).astype(np.float32)
    out = augment.jax_random_crop_with_pad(imgs, jax.random.key(0), pad=2)
    assert out.shape == (4, 8, 8, 3)


# --------------------------------------------------------------------------
# Datasets
# --------------------------------------------------------------------------


def test_array_dataset_epochs_and_resume():
    x = np.arange(20, dtype=np.float32).reshape(20, 1)
    y = np.arange(20, dtype=np.int32)
    ds = datasets.ArrayDataset({"image": x, "label": y}, 4, seed=11)
    it = iter(ds)
    seen = [next(it) for _ in range(7)]  # crosses an epoch boundary
    state = ds.get_state()

    ds2 = datasets.ArrayDataset({"image": x, "label": y}, 4, seed=11)
    ds2.set_state(state)
    resumed = [next(iter(ds2)) for _ in range(3)]

    ds3 = datasets.ArrayDataset({"image": x, "label": y}, 4, seed=11)
    full = [next(iter(ds3)) for _ in range(10)]
    for a, b in zip(seen + resumed, full):
        np.testing.assert_array_equal(a["label"], b["label"])

    # Every epoch covers all samples exactly once.
    labels = np.concatenate([b["label"] for b in full[:5]])
    assert sorted(labels.tolist()) == list(range(20))


def test_mnist_cifar_shapes():
    b = next(iter(datasets.mnist_dataset(8)))
    assert b["image"].shape == (8, 28, 28, 1)
    b = next(iter(datasets.cifar10_dataset(8)))
    assert b["image"].shape == (8, 32, 32, 3)
    assert b["image"].dtype == np.float32
    # standardized: roughly zero mean per image
    assert abs(b["image"][0].mean()) < 0.1


def test_imagenet_tfrecord_dataset(tmp_path):
    paths = []
    rs = np.random.RandomState(0)
    for s in range(2):
        recs = []
        for i in range(6):
            img = (rs.rand(48, 56, 3) * 255).astype(np.uint8)
            recs.append(
                example_proto.build_example(
                    {
                        "image/encoded": [augment.encode_jpeg(img)],
                        "image/class/label": [1 + (s * 6 + i) % 10],
                    }
                )
            )
        p = str(tmp_path / f"train-{s:05d}")
        tfrecord.write_records(p, recs)
        paths.append(p)

    ds = datasets.ImageNetTFRecordDataset(
        paths, 4, train=True, image_size=32, label_offset=1
    )
    batch = next(iter(ds))
    assert batch["image"].shape == (4, 32, 32, 3)
    assert batch["label"].min() >= 0 and batch["label"].max() < 10

    state = ds.get_state()
    ds2 = datasets.ImageNetTFRecordDataset(
        paths, 4, train=True, image_size=32, label_offset=1
    )
    ds2.set_state(state)
    b2 = next(iter(ds2))
    b_cont = next(iter(ds))
    np.testing.assert_array_equal(b2["label"], b_cont["label"])


def test_ptb_dataset_windows_and_resume():
    tokens = np.arange(100, dtype=np.int32)
    ds = datasets.PTBDataset(tokens, batch_size=4, num_steps=5)
    it = iter(ds)
    b0 = next(it)
    assert b0["inputs"].shape == (4, 5)
    np.testing.assert_array_equal(b0["targets"], b0["inputs"] + 1)
    b1 = next(it)
    np.testing.assert_array_equal(b1["inputs"], b0["inputs"] + 5)

    state = ds.get_state()
    ds2 = datasets.PTBDataset(tokens, batch_size=4, num_steps=5)
    ds2.set_state(state)
    np.testing.assert_array_equal(next(iter(ds2))["inputs"], next(it)["inputs"])


def test_example_numpy_scalars_encode_correctly():
    feats = {
        "bbox": [np.float32(0.37), np.float32(0.9)],
        "label": [np.int64(3)],
    }
    parsed = example_proto.parse_example(example_proto.build_example(feats))
    np.testing.assert_allclose(parsed["bbox"], [0.37, 0.9], rtol=1e-6)
    assert parsed["label"] == [3]


def test_imagenet_eval_is_one_pass_with_partial_batch(tmp_path):
    recs = []
    for i in range(10):
        img = np.full((24, 24, 3), i * 20, np.uint8)
        recs.append(
            example_proto.build_example(
                {
                    "image/encoded": [augment.encode_jpeg(img)],
                    "image/class/label": [i],
                }
            )
        )
    p = str(tmp_path / "val-00000")
    tfrecord.write_records(p, recs)
    ds = datasets.ImageNetTFRecordDataset(
        [p], 4, train=False, image_size=16
    )
    batches = list(ds)
    assert [len(b["label"]) for b in batches] == [4, 4, 2]
    assert sorted(np.concatenate([b["label"] for b in batches])) == list(
        range(10)
    )


def test_sharded_iterator_native_true_requires_library(tmp_path):
    from distributed_tensorflow_models_tpu.data import native_loader

    p = str(tmp_path / "s")
    tfrecord.write_records(p, [b"x"])
    it = tfrecord.ShardedRecordIterator([p], native=True)
    if native_loader.available():
        assert next(iter(it)) == b"x"
    else:
        with pytest.raises(RuntimeError, match="native=True"):
            next(iter(it))


def test_synthetic_imagenet():
    ds = datasets.synthetic_imagenet_dataset(16, image_size=8)
    b = next(iter(ds))
    assert b["image"].shape == (16, 8, 8, 3)
    assert b["label"].max() < 1000


# --------------------------------------------------------------------------
# Host pipeline + device prefetch
# --------------------------------------------------------------------------


def test_host_pipeline_order_and_state():
    x = np.arange(24, dtype=np.float32).reshape(24, 1)
    y = np.arange(24, dtype=np.int32)
    ds = datasets.ArrayDataset({"image": x, "label": y}, 4, seed=2)
    pipe = pipeline.HostPipeline(ds, prefetch=2)
    got = [next(pipe) for _ in range(4)]
    state = pipe.get_state()
    pipe.stop()

    # Resume from the captured state reproduces the continuation.
    ds2 = datasets.ArrayDataset({"image": x, "label": y}, 4, seed=2)
    ds2.set_state(state)
    pipe2 = pipeline.HostPipeline(ds2, prefetch=2)
    b_resume = next(pipe2)
    pipe2.stop()

    ds3 = datasets.ArrayDataset({"image": x, "label": y}, 4, seed=2)
    ref = [next(iter(ds3)) for _ in range(5)]
    for a, b in zip(got, ref[:4]):
        np.testing.assert_array_equal(a["label"], b["label"])
    np.testing.assert_array_equal(b_resume["label"], ref[4]["label"])


def test_host_pipeline_propagates_errors():
    def bad_gen():
        yield {"x": np.zeros(1)}
        raise RuntimeError("producer exploded")

    pipe = pipeline.HostPipeline(bad_gen(), prefetch=1)
    next(pipe)
    with pytest.raises(RuntimeError, match="producer exploded"):
        next(pipe)
        next(pipe)


def test_host_pipeline_worker_count_invariance():
    """The pool contract: the emitted stream is bit-identical for any
    data_workers — ImageNet-synthetic (plain slicing) and CIFAR train
    (per-sample augmentation, rngs keyed by global sample position)."""
    builders = {
        "imagenet_synthetic": lambda: datasets.synthetic_imagenet_dataset(
            8, image_size=32, seed=7
        ),
        "cifar_augmented": lambda: datasets.cifar10_dataset(
            8, "train", seed=3
        ),
    }
    for name, fresh in builders.items():
        ref_it = iter(fresh())
        ref = [next(ref_it) for _ in range(10)]
        for workers in (1, 4):
            pipe = pipeline.HostPipeline(
                fresh(), prefetch=2, num_workers=workers
            )
            got = [next(pipe) for _ in range(10)]
            state = pipe.get_state()
            pipe.stop()
            for a, b in zip(got, ref):
                np.testing.assert_array_equal(
                    a["image"], b["image"], err_msg=f"{name} w={workers}"
                )
                np.testing.assert_array_equal(a["label"], b["label"])
            # State follows the last released batch regardless of pool
            # width: position 10, exactly where the serial path stands.
            assert state == {"epoch": 0, "batch_idx": 10}, (name, workers)


def test_host_pipeline_worker_pool_tfrecord_decode(tmp_path):
    """The decode-bound path through the pool: TFRecord shards → JPEG
    decode + distorted-bbox augment in parallel workers, stream and
    resume state identical to the serial iterator."""
    rs = np.random.RandomState(1)
    recs = []
    for i in range(12):
        img = (rs.rand(40, 40, 3) * 255).astype(np.uint8)
        recs.append(
            example_proto.build_example(
                {
                    "image/encoded": [augment.encode_jpeg(img)],
                    "image/class/label": [1 + i % 10],
                }
            )
        )
    p = str(tmp_path / "train-00000")
    tfrecord.write_records(p, recs)

    def fresh():
        return datasets.ImageNetTFRecordDataset(
            [p], 4, train=True, image_size=32, label_offset=1, seed=11
        )

    ref_it = iter(fresh())
    ref = [next(ref_it) for _ in range(5)]  # loops epochs past 12 records

    pipe = pipeline.HostPipeline(fresh(), prefetch=2, num_workers=2)
    got = [next(pipe) for _ in range(5)]
    state = pipe.get_state()
    pipe.stop()
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["label"], b["label"])

    # Resume from the pool-produced state = the serial continuation.
    ds2 = fresh()
    ds2.set_state(state)
    b_resume = next(iter(ds2))
    b_expect = next(ref_it)
    np.testing.assert_array_equal(b_resume["image"], b_expect["image"])
    np.testing.assert_array_equal(b_resume["label"], b_expect["label"])


class _ExplodingDataset:
    """Pool-protocol dataset whose assemble fails at one index; earlier
    items finish deliberately out of order (later index = faster)."""

    def __init__(self, boom_at=3):
        self._i = 0
        self._boom_at = boom_at

    def next_work(self):
        w = self._i
        self._i += 1
        return w

    def assemble(self, w):
        if w == self._boom_at:
            raise RuntimeError(f"boom at {w}")
        import time

        time.sleep(0.005 * (self._boom_at + 1 - min(w, self._boom_at)))
        return {"x": np.full((2,), w, np.float32)}

    def get_state(self):
        return {"i": self._i}


def test_host_pipeline_pool_error_surfaces_at_position():
    """Coordinator contract under the pool: every good batch before the
    failure index drains in order, THEN the error raises."""
    pipe = pipeline.HostPipeline(
        _ExplodingDataset(boom_at=3), prefetch=4, num_workers=4
    )
    got = []
    with pytest.raises(RuntimeError, match="boom at 3"):
        for _ in range(10):
            got.append(float(next(pipe)["x"][0]))
    assert got == [0.0, 1.0, 2.0]
    pipe.stop()  # error already consumed: must not re-raise


def test_host_pipeline_stop_raises_pending_error():
    """stop() must not silently drop a producer error the consumer never
    reached (the old pipeline.py:129-138 behavior)."""
    import time

    pipe = pipeline.HostPipeline(
        _ExplodingDataset(boom_at=2), prefetch=4, num_workers=2
    )
    assert float(next(pipe)["x"][0]) == 0.0
    for _ in range(200):  # wait for the failure to reach reassembly
        if pipe._error is not None:
            break
        time.sleep(0.01)
    with pytest.raises(RuntimeError, match="boom at 2"):
        pipe.stop()


def test_host_pipeline_stop_finds_error_still_in_flight():
    """A failure a worker produced but reassembly never walked past
    (blocked on a full consumer buffer) must still surface from stop()
    — swept from the in-flight queues, not silently dropped."""
    import time

    # prefetch=1 and no consumption: reassembly releases batch 0, blocks
    # on the full buffer; the failure at index 2 stays in flight.
    pipe = pipeline.HostPipeline(
        _ExplodingDataset(boom_at=2), prefetch=1, num_workers=2
    )
    for _ in range(200):  # wait until the failing assemble has run
        with pipe._results_q.mutex:
            in_q = any(
                isinstance(p, pipeline._Failure)
                for _, p, _ in list(pipe._results_q.queue)
            )
        in_pending = any(
            isinstance(p, pipeline._Failure)
            for p, _ in list(pipe._pending.values())
        )
        if in_q or in_pending or pipe._error is not None:
            break
        time.sleep(0.01)
    with pytest.raises(RuntimeError, match="boom at 2"):
        pipe.stop()


def test_host_pipeline_pool_winds_down_after_error():
    """After a mid-stream producer error the pool must stop feeding the
    results queue (an infinite dataset would otherwise free-run into
    unbounded memory while the consumer drains toward the error)."""
    import time

    pipe = pipeline.HostPipeline(
        _ExplodingDataset(boom_at=2), prefetch=4, num_workers=2
    )
    with pytest.raises(RuntimeError, match="boom at 2"):
        for _ in range(10):
            next(pipe)
    assert pipe._pool_stop.wait(timeout=2.0)
    for t in pipe._threads:
        t.join(timeout=2.0)
    assert not any(t.is_alive() for t in pipe._threads)
    assert pipe._results_q.qsize() <= 8  # bounded in-flight, not free-run
    pipe.stop()


def test_host_pipeline_pool_falls_back_without_protocol():
    """A plain iterable (no next_work/assemble) with num_workers>1 warns
    and degrades to the serial producer — never breaks."""

    def gen():
        for i in range(4):
            yield {"x": np.full((2,), i, np.float32)}

    pipe = pipeline.HostPipeline(gen(), prefetch=2, num_workers=4)
    got = [float(next(pipe)["x"][0]) for _ in range(4)]
    assert got == [0.0, 1.0, 2.0, 3.0]
    with pytest.raises(StopIteration):
        next(pipe)
    pipe.stop()


def test_host_queue_depth_reads_zero_when_drained():
    """The gauge is sampled on the consumer side too: after the stream is
    fully drained it must read 0, not the last produced depth."""
    from distributed_tensorflow_models_tpu import telemetry

    def gen():
        for i in range(3):
            yield {"x": np.full((2,), i, np.float32)}

    reg = telemetry.MetricsRegistry()
    pipe = pipeline.HostPipeline(gen(), prefetch=4, registry=reg)
    for _ in range(3):
        next(pipe)
    with pytest.raises(StopIteration):
        next(pipe)
    assert reg.gauge(telemetry.HOST_QUEUE_DEPTH).value == 0.0
    pipe.stop()


def test_device_prefetcher(mesh8):
    x = np.arange(64, dtype=np.float32).reshape(8, 8)
    y = np.arange(8, dtype=np.int32)
    ds = datasets.ArrayDataset({"image": x, "label": y}, 8, seed=0)
    pre = pipeline.DevicePrefetcher(ds, mesh8, depth=2)
    batch = next(pre)
    import jax

    assert isinstance(batch["image"], jax.Array)
    assert batch["image"].shape == (8, 8)
    # Sharded over the data axis of the mesh.
    assert not batch["image"].sharding.is_fully_replicated


# --------------------------------------------------------------------------
# Multi-host sharding (SURVEY.md §3.4: per-worker input streams)
# --------------------------------------------------------------------------


def test_array_dataset_process_shards_concat_to_global_batch():
    """Process-order concatenation of per-process slices must reproduce the
    single-process global batch exactly — including deterministic
    augmentation (rngs keyed by global sample position)."""
    full = datasets.cifar10_dataset(8, "train", seed=3)
    parts = [
        datasets.cifar10_dataset(
            8, "train", seed=3, process_index=p, process_count=2
        )
        for p in range(2)
    ]
    fit, pits = iter(full), [iter(p) for p in parts]
    for _ in range(3):  # spans an epoch boundary reshuffle at 8192/8
        fb = next(fit)
        pbs = [next(it) for it in pits]
        assert all(pb["image"].shape[0] == 4 for pb in pbs)
        np.testing.assert_array_equal(
            fb["image"], np.concatenate([pb["image"] for pb in pbs])
        )
        np.testing.assert_array_equal(
            fb["label"], np.concatenate([pb["label"] for pb in pbs])
        )


def test_array_dataset_rejects_indivisible_process_count():
    with pytest.raises(ValueError):
        datasets.mnist_dataset(8, process_index=0, process_count=3)


def test_ptb_dataset_process_shards_are_row_blocks():
    tokens = np.arange(100, dtype=np.int32)
    full = datasets.PTBDataset(tokens, batch_size=4, num_steps=5)
    parts = [
        datasets.PTBDataset(
            tokens,
            batch_size=4,
            num_steps=5,
            process_index=p,
            process_count=2,
        )
        for p in range(2)
    ]
    fb = next(iter(full))
    pbs = [next(iter(p)) for p in parts]
    np.testing.assert_array_equal(
        fb["inputs"], np.concatenate([pb["inputs"] for pb in pbs])
    )
    np.testing.assert_array_equal(
        fb["targets"], np.concatenate([pb["targets"] for pb in pbs])
    )


def _write_imagenet_shards(tmp_path, n_shards, per_shard, prefix="train"):
    paths = []
    for s in range(n_shards):
        recs = []
        for i in range(per_shard):
            img = np.full((24, 24, 3), (s * per_shard + i) * 5, np.uint8)
            recs.append(
                example_proto.build_example(
                    {
                        "image/encoded": [augment.encode_jpeg(img)],
                        "image/class/label": [s * per_shard + i],
                    }
                )
            )
        p = str(tmp_path / f"{prefix}-{s:05d}")
        tfrecord.write_records(p, recs)
        paths.append(p)
    return paths


def test_imagenet_train_file_sharding_is_disjoint(tmp_path):
    paths = _write_imagenet_shards(tmp_path, n_shards=2, per_shard=6)
    parts = [
        datasets.ImageNetTFRecordDataset(
            paths,
            4,
            train=True,
            image_size=16,
            process_index=p,
            process_count=2,
        )
        for p in range(2)
    ]
    seen = []
    for part in parts:
        it = iter(part)
        labels = np.concatenate([next(it)["label"] for _ in range(3)])
        assert len(labels) == 6  # local batch 2, file of 6 records
        seen.append(set(labels.tolist()))
    # Each process consumed exactly one whole shard file; no overlap.
    assert seen[0] | seen[1] == set(range(12))
    assert not (seen[0] & seen[1])


def test_imagenet_train_replicated_fallback_matches_global(tmp_path):
    """With fewer shard files than processes the dataset falls back to
    replicated reads + row slicing, which must reproduce the single-process
    batches exactly (augment rng keyed by global record count)."""
    paths = _write_imagenet_shards(tmp_path, n_shards=1, per_shard=8)
    full = datasets.ImageNetTFRecordDataset(
        paths, 4, train=True, image_size=16, seed=7
    )
    parts = [
        datasets.ImageNetTFRecordDataset(
            paths,
            4,
            train=True,
            image_size=16,
            seed=7,
            process_index=p,
            process_count=2,
        )
        for p in range(2)
    ]
    fb = next(iter(full))
    pbs = [next(iter(p)) for p in parts]
    np.testing.assert_array_equal(
        fb["image"], np.concatenate([pb["image"] for pb in pbs])
    )
    np.testing.assert_array_equal(
        fb["label"], np.concatenate([pb["label"] for pb in pbs])
    )


def test_imagenet_eval_multiprocess_pads_final_batch(tmp_path):
    paths = _write_imagenet_shards(
        tmp_path, n_shards=1, per_shard=10, prefix="val"
    )
    parts = [
        datasets.ImageNetTFRecordDataset(
            paths,
            4,
            train=False,
            image_size=16,
            process_index=p,
            process_count=2,
        )
        for p in range(2)
    ]
    batches = [list(p) for p in parts]
    # 10 records, global batch 4 -> 3 global batches, last padded.
    assert [len(bs) for bs in batches] == [3, 3]
    for bs in batches:
        assert all(b["label"].shape == (2,) for b in bs)
    labels = np.stack(
        [np.concatenate([b["label"] for b in bs]) for bs in batches]
    )
    # Row blocks interleave back into the global record order.
    merged = np.concatenate(
        [
            np.stack([labels[0, i * 2 : i * 2 + 2],
                      labels[1, i * 2 : i * 2 + 2]]).reshape(-1)
            for i in range(3)
        ]
    )
    np.testing.assert_array_equal(
        merged, np.array([0, 1, 2, 3, 4, 5, 6, 7, 8, 9, -1, -1])
    )
