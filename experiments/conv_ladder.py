#!/usr/bin/env python
"""Graded conv-compile probe for the axon TPU relay.

Matmul-dominated programs (PTB LSTM, transformer, Pallas attention) compile
and run through the relay; the ResNet-50 train step's remote compile hangs
it (round-1 and round-2 evidence, experiments/TPU_BENCH_r2.md).  No conv
program has ever been observed to compile through this relay — this script
bisects where it breaks, one rung per subprocess with a hard timeout so a
wedge is contained and *recorded* instead of killing the run.

Run rungs in order, cheapest first; stop at the first timeout (the wedge
poisons the backend for every later rung anyway).

Usage: python experiments/conv_ladder.py [--timeout 420] [--out FILE]
"""

# Runnable from anywhere (same idiom as recompute_mfu.py).
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import json
import subprocess
import time

RUNGS = {
    # name -> python source run in a fresh process; prints OK on success
    "conv_op": """
import jax, jax.numpy as jnp
x = jnp.ones((8, 32, 32, 16))
w = jnp.ones((3, 3, 16, 32))
y = jax.jit(lambda x, w: jax.lax.conv_general_dilated(
    x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC")))(x, w)
print("OK", y.shape, jax.devices()[0].device_kind)
""",
    "lenet_train": """
import jax, jax.numpy as jnp, numpy as np
from distributed_tensorflow_models_tpu.core import mesh as meshlib, sharding as shardlib, train_loop
from distributed_tensorflow_models_tpu.core.train_state import TrainState
from distributed_tensorflow_models_tpu.models import get_model
from distributed_tensorflow_models_tpu.ops import optim
mesh = meshlib.data_parallel_mesh()
model = get_model("lenet")
state = TrainState.create(model, optim.sgd(0.01), jax.random.key(0),
                          jnp.zeros((8, 28, 28, 1), jnp.float32))
state = train_loop.place_state(state, mesh)
step = train_loop.make_train_step_fn(train_loop.classification_loss_fn(model.apply))
rng = np.random.RandomState(0)
batch = shardlib.shard_batch(mesh, {"image": rng.rand(32, 28, 28, 1).astype(np.float32),
                                    "label": rng.randint(0, 10, (32,))})
state, m = jax.jit(step)(state, batch, jax.random.key(1))
print("OK loss", float(m["loss"]))
""",
    "resnet32_train": """
import jax, jax.numpy as jnp, numpy as np
from distributed_tensorflow_models_tpu.core import mesh as meshlib, sharding as shardlib, train_loop
from distributed_tensorflow_models_tpu.core.train_state import TrainState
from distributed_tensorflow_models_tpu.models import get_model
from distributed_tensorflow_models_tpu.ops import optim
mesh = meshlib.data_parallel_mesh()
model = get_model("resnet32")
state = TrainState.create(model, optim.sgd(0.01), jax.random.key(0),
                          jnp.zeros((8, 32, 32, 3), jnp.float32))
state = train_loop.place_state(state, mesh)
step = train_loop.make_train_step_fn(train_loop.classification_loss_fn(model.apply))
rng = np.random.RandomState(0)
batch = shardlib.shard_batch(mesh, {"image": rng.rand(64, 32, 32, 3).astype(np.float32),
                                    "label": rng.randint(0, 10, (64,))})
state, m = jax.jit(step)(state, batch, jax.random.key(1))
print("OK loss", float(m["loss"]))
""",
    "resnet50_fwd_b8": """
import jax, jax.numpy as jnp
from distributed_tensorflow_models_tpu.models import get_model
model = get_model("resnet50")
params = model.init(jax.random.key(0), jnp.zeros((8, 224, 224, 3)))
logits = jax.jit(lambda p, x: model.apply(p, x))(params, jnp.ones((8, 224, 224, 3)))
print("OK", logits[0].shape if isinstance(logits, tuple) else logits.shape)
""",
    "resnet50_train_b32": """
import jax, jax.numpy as jnp, numpy as np
from distributed_tensorflow_models_tpu.core import mesh as meshlib, sharding as shardlib, train_loop
from distributed_tensorflow_models_tpu.core.train_state import TrainState
from distributed_tensorflow_models_tpu.models import get_model
from distributed_tensorflow_models_tpu.ops import optim
mesh = meshlib.data_parallel_mesh()
model = get_model("resnet50")
state = TrainState.create(model, optim.tf_momentum(0.1, 0.9), jax.random.key(0),
                          jnp.zeros((8, 224, 224, 3), jnp.float32))
state = train_loop.place_state(state, mesh)
step = train_loop.make_train_step_fn(
    train_loop.classification_loss_fn(model.apply, weight_decay=1e-4))
rng = np.random.RandomState(0)
batch = shardlib.shard_batch(mesh, {"image": rng.rand(32, 224, 224, 3).astype(np.float32),
                                    "label": rng.randint(0, 1000, (32,))})
state, m = jax.jit(step)(state, batch, jax.random.key(1))
print("OK loss", float(m["loss"]))
""",
    "resnet50_train_b256": """
import jax, jax.numpy as jnp, numpy as np
from distributed_tensorflow_models_tpu.core import mesh as meshlib, sharding as shardlib, train_loop
from distributed_tensorflow_models_tpu.core.train_state import TrainState
from distributed_tensorflow_models_tpu.models import get_model
from distributed_tensorflow_models_tpu.ops import optim
mesh = meshlib.data_parallel_mesh()
model = get_model("resnet50")
state = TrainState.create(model, optim.tf_momentum(0.1, 0.9), jax.random.key(0),
                          jnp.zeros((8, 224, 224, 3), jnp.float32))
state = train_loop.place_state(state, mesh)
step = train_loop.make_train_step_fn(
    train_loop.classification_loss_fn(model.apply, weight_decay=1e-4))
rng = np.random.RandomState(0)
batch = shardlib.shard_batch(mesh, {"image": rng.rand(256, 224, 224, 3).astype(np.float32),
                                    "label": rng.randint(0, 1000, (256,))})
state, m = jax.jit(step)(state, batch, jax.random.key(1))
print("OK loss", float(m["loss"]))
""",
}


def main():
    # Deferral sentinel: the native-conv rungs are the one program class
    # that historically WEDGES the relay, so a chained runner that still
    # has matmul-class benches to bank can park this probe until it is
    # the only thing left.  Touch the file to defer, remove to re-arm.
    sentinel = "/tmp/dtm_defer_native_ladder"
    if os.path.exists(sentinel):
        print(
            f"native conv ladder deferred: sentinel {sentinel} exists",
            file=sys.stderr,
        )
        print(json.dumps({"deferred": True}))
        return

    p = argparse.ArgumentParser()
    p.add_argument("--timeout", type=float, default=420.0)
    p.add_argument(
        "--out",
        default=os.path.join(os.path.dirname(__file__), "conv_ladder.json"),
    )
    p.add_argument("--rungs", nargs="*", default=list(RUNGS))
    args = p.parse_args()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    results = {}
    for name in args.rungs:
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, "-c", RUNGS[name]],
                timeout=args.timeout,
                capture_output=True,
                text=True,
                cwd=repo,
            )
            ok = proc.returncode == 0 and "OK" in proc.stdout
            results[name] = {
                "ok": ok,
                "seconds": round(time.time() - t0, 1),
                "detail": (proc.stdout + proc.stderr).strip()[-300:],
            }
        except subprocess.TimeoutExpired:
            results[name] = {
                "ok": False,
                "seconds": round(time.time() - t0, 1),
                "detail": f"TIMEOUT {args.timeout}s (relay wedge)",
            }
        print(f"{name}: {results[name]}", file=sys.stderr, flush=True)
        with open(args.out, "w") as f:
            json.dump(results, f, indent=1)
        if not results[name]["ok"]:
            print(f"stopping at first failure: {name}", file=sys.stderr)
            break
    print(json.dumps(results))


if __name__ == "__main__":
    main()
