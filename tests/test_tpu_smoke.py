"""1-chip TPU smoke (SURVEY.md §4 item 4): N steps of a proven-compile
config on the real chip — loss decrease, checkpoint round-trip,
steps/sec floor.

Off by default (DTM_TPU_SMOKE=1 enables): the suite's conftest pins
every test process to the 8-device CPU mesh, and this machine's relay
wedges for hours at a time — an unconditional TPU test would either hang
collection or add a probe timeout to every CI run.  The smoke therefore
(a) requires explicit opt-in, (b) probes the relay in a hard-killed
subprocess before committing to anything (the tpu_gate_lib.sh probe
contract), and (c) runs the actual training steps in a fresh subprocess
with the axon plugin on PYTHONPATH (conftest already pinned THIS process
to cpu).  The gated recovery queue runs it as a banked artifact
(experiments/tpu_r4_smoke.json).
"""

import json
import os
import subprocess
import sys

import pytest

_SMOKE = os.environ.get("DTM_TPU_SMOKE") == "1"

_PROBE = (
    "import jax; d = jax.devices(); "
    "assert d[0].platform == 'tpu', d[0].platform; "
    "import jax.numpy as jnp; "
    "x = jnp.ones((256, 256), jnp.bfloat16); "
    "(x @ x).block_until_ready(); print('ok')"
)

_SMOKE_BODY = """
import json, time
import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_tensorflow_models_tpu.core import mesh as meshlib
from distributed_tensorflow_models_tpu.core import train_loop
from distributed_tensorflow_models_tpu.core.train_state import TrainState
from distributed_tensorflow_models_tpu.harness import checkpoint as ckptlib
from distributed_tensorflow_models_tpu.models import get_model
from distributed_tensorflow_models_tpu.ops import optim

assert jax.devices()[0].platform == "tpu"
T = 128
model = get_model(
    "transformer_lm", num_layers=2, num_heads=4, d_model=128,
    d_ff=512, max_len=T, dropout_rate=0.0,
)
mesh = meshlib.data_parallel_mesh()
tx = optax.chain(optim.clip_by_global_norm(1.0), optim.adam(1e-3))
state = TrainState.create(
    model, tx, jax.random.key(0), jnp.zeros((2, T), jnp.int32)
)
state = train_loop.place_state(state, mesh)
loss_fn = train_loop.lm_loss_fn(model.apply, fused_unembed=True)
step = jax.jit(train_loop.make_train_step_fn(loss_fn))
rng = np.random.RandomState(0)
tok = jnp.asarray(rng.randint(0, 10000, (16, T + 1)), jnp.int32)
batch = {"inputs": tok[:, :-1], "targets": tok[:, 1:]}
losses = []
state, m = step(state, batch, jax.random.key(0))  # compile
t0 = time.perf_counter()
N = 20
for i in range(N):
    state, m = step(state, batch, jax.random.key(i))
    losses.append(float(m["loss"]))
jax.block_until_ready(state.params)
dt = time.perf_counter() - t0
# Checkpoint round-trip (restore_or_init returns (state, data, restored)).
import tempfile
with tempfile.TemporaryDirectory() as d:
    mgr = ckptlib.CheckpointManager(d, keep=1)
    mgr.save(state, force=True)
    mgr.wait()
    restored, _, was_restored = ckptlib.restore_or_init(mgr, state)
    assert was_restored
    assert int(restored.step) == int(state.step)
print(json.dumps({
    "loss_first": losses[0],
    "loss_last": losses[-1],
    "steps_per_sec": N / dt,
    "platform": jax.devices()[0].platform,
}))
"""


@pytest.mark.skipif(
    not _SMOKE, reason="TPU smoke is opt-in (DTM_TPU_SMOKE=1)"
)
def test_tpu_one_chip_smoke():
    env = dict(os.environ)
    # The TPU path needs the axon plugin on PYTHONPATH and must NOT
    # inherit the conftest's CPU pin (that pin is in-process only, but
    # XLA_FLAGS fake-device count leaks through env).
    env.pop("JAX_PLATFORMS", None)
    env["XLA_FLAGS"] = " ".join(
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    )
    try:
        probe = subprocess.run(
            [sys.executable, "-c", _PROBE],
            timeout=90, capture_output=True, text=True, env=env,
        )
    except subprocess.TimeoutExpired:
        pytest.skip("relay unhealthy: devices() hung past the 90s probe")
    if probe.returncode != 0 or "ok" not in probe.stdout:
        pytest.skip(
            f"relay unhealthy: {(probe.stderr or probe.stdout)[-200:]}"
        )
    run = subprocess.run(
        [sys.executable, "-c", _SMOKE_BODY],
        timeout=600, capture_output=True, text=True, env=env,
    )
    assert run.returncode == 0, run.stderr[-2000:]
    out = json.loads(run.stdout.strip().splitlines()[-1])
    assert out["platform"] == "tpu"
    assert out["loss_last"] < out["loss_first"], out
    # Regression floor: the flagship chip does hundreds of these small
    # steps per second; even a badly degraded relay session manages >2.
    assert out["steps_per_sec"] > 2.0, out
    # Artifact emission happens ONLY after every assertion passed, so a
    # banked file is a success marker by construction (the gated runner
    # greps it; pytest chatter goes to the log, never the artifact).
    artifact = os.environ.get("DTM_SMOKE_OUT")
    if artifact:
        with open(artifact, "w") as f:
            json.dump({"metric": "tpu_smoke", **out}, f)
    print(json.dumps(out))
