#!/usr/bin/env python
"""Collate every round-4 TPU artifact into one markdown table.

Reads ``experiments/tpu_r4_*.json`` (the one-line bench outputs) and
prints | artifact | metric | value | unit | MFU | platform | — errors
and empty files are listed separately so a partially-banked queue is
visible at a glance.  Used to refresh TPU_BENCH_r4.md after the gated
runners drain; writes nothing itself.
"""

from __future__ import annotations

import glob
import json
import os
import sys


def main() -> int:
    here = os.path.dirname(os.path.abspath(__file__))
    rows, errors, empty = [], [], []
    for path in sorted(glob.glob(os.path.join(here, "tpu_r4_*.json"))):
        name = os.path.basename(path)
        if name.endswith("_detail.json"):
            continue
        try:
            with open(path) as f:
                text = f.read().strip()
        except OSError as e:
            errors.append((name, f"unreadable: {e}"))
            continue
        if not text:
            empty.append(name)
            continue
        try:
            d = json.loads(text.splitlines()[-1])
        except json.JSONDecodeError as e:
            errors.append((name, f"bad json: {e}"))
            continue
        if "error" in d:
            errors.append((name, str(d["error"])[:100]))
            continue
        mfu = d.get("mfu")
        metric = d.get("metric", "?")
        if d.get("config_errors"):
            # A partial (e.g. watchdog-truncated) run still carries a
            # headline; flag it so the table can't pass it off as a
            # clean full-queue result.
            bad = ", ".join(sorted(d["config_errors"]))
            metric += f" (PARTIAL: {bad} errored)"
        rows.append(
            (
                name,
                metric,
                d.get("value"),
                d.get("unit", ""),
                f"{mfu:.1%}" if isinstance(mfu, float) else "—",
                d.get("platform", "?"),
            )
        )

    print("| artifact | metric | value | unit | MFU | platform |")
    print("|---|---|---|---|---|---|")
    for r in rows:
        print("| " + " | ".join(str(x) for x in r) + " |")
    if errors:
        print("\nErrored artifacts:\n")
        for name, err in errors:
            print(f"- `{name}` — {err}")
    if empty:
        print("\nEmpty (in-flight or killed):\n")
        for name in empty:
            print(f"- `{name}`")
    return 0


if __name__ == "__main__":
    sys.exit(main())
