"""Known-bad: swap reads a donated arena; wall-clock version pick."""
import time


class Swapper:
    def __init__(self, fn):
        self._decode = jax.jit(fn, donate_argnums=(1,))

    def swap_and_step(self, params, arena, tok, new_params):
        out = self._decode(params, arena, tok)
        self.params = new_params
        return out, arena.sum()


def pick_version(primary, canary):
    if time.time() % 2.0 < 1.0:
        return canary
    return primary
