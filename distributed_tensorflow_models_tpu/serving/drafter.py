"""N-gram self-drafter for speculative decoding — jax-free by design.

Prompt-lookup drafting (the no-second-model end of the speculative
decoding family): the request's own token history — prompt plus
everything generated so far — is the draft model.  When the last
``n`` tokens have appeared before, the tokens that followed that
earlier occurrence are proposed as the continuation.  Chat and code
traffic is highly repetitive (restated prompts, copied identifiers,
templated boilerplate), so suffix matches are common exactly where
speculation pays; on incompressible traffic the drafter simply finds
no match and proposes nothing, which the engine turns into a plain
burst dispatch (zero drafting overhead on the device).

Host-side and stdlib+numpy only: proposals are DATA fed to the one
decode program (``engine.py``), never traced, so the drafter can use
dicts and Python ints freely without touching the compile-count pin.
The verify rule in the engine — a draft is accepted iff the target's
own sample (with that position's ``key_schedule`` key) equals it —
means a drafter can only ever cost throughput, never change a token:
byte-identity to solo ``generate()`` holds at ANY acceptance rate, so
this module needs to be fast and honest, not correct-by-proof.

Matching is longest-first: orders ``ngram_order`` down to
``min_match`` are tried in turn, and within an order the MOST RECENT
earlier occurrence wins (recency tracks the local phrase distribution
better than the first occurrence).  Tables are per-request and
incremental — O(orders) dict updates per appended token, O(orders)
lookups per proposal — so drafting adds microseconds to a scheduler
iteration whose device dispatch costs milliseconds.
"""

from __future__ import annotations

import numpy as np

# Proposal slots the drafter leaves empty.  Device-side the engine
# clamps these to token 0 before the embedding lookup (the positions
# are inert: acceptance stops at the first pad, so their samples and
# KV writes are discarded/overwritten); host-side -1 can never equal a
# real vocab token, so a padded slot can never be "accepted" even by a
# garbage sample collision.
NO_DRAFT = -1


class NgramDrafter:
    """Per-request suffix-match table over prompt + generated history.

    ``propose`` returns an int32 ``[spec_tokens]`` vector padded with
    :data:`NO_DRAFT`; ``append`` must be called with every token the
    scheduler emits for this request (the same stream the model saw),
    or proposals drift from the true context and acceptance decays —
    never correctness, which the engine's verify rule owns.
    """

    def __init__(self, prompt, *, spec_tokens: int, ngram_order: int = 3,
                 min_match: int = 1):
        if spec_tokens < 1:
            raise ValueError(
                f"spec_tokens must be >= 1, got {spec_tokens}"
            )
        if min_match < 1:
            raise ValueError(
                f"min_match must be >= 1, got {min_match}"
            )
        if ngram_order < min_match:
            raise ValueError(
                f"ngram_order {ngram_order} must be >= min_match "
                f"{min_match}"
            )
        self.spec_tokens = int(spec_tokens)
        self.ngram_order = int(ngram_order)
        self.min_match = int(min_match)
        self._hist: list = []
        # (order, gram) -> end index of its latest occurrence; _prev
        # holds the occurrence before that.  The current suffix is
        # itself the latest occurrence of its own grams, so propose()
        # steps back to _prev when _last points at the suffix.
        self._last: dict = {}
        self._prev: dict = {}
        for tok in np.asarray(prompt).reshape(-1):
            self.append(int(tok))

    def append(self, token: int) -> None:
        """Extend the history by one emitted token and index the grams
        that now end at it."""
        self._hist.append(int(token))
        j = len(self._hist) - 1
        for n in range(self.min_match, self.ngram_order + 1):
            if j + 1 < n:
                break
            key = (n, tuple(self._hist[j + 1 - n: j + 1]))
            if key in self._last:
                self._prev[key] = self._last[key]
            self._last[key] = j

    def propose(self) -> np.ndarray:
        """Up to ``spec_tokens`` continuation tokens for the current
        suffix, :data:`NO_DRAFT`-padded; all-padding when no suffix of
        length >= ``min_match`` has occurred before."""
        out = np.full((self.spec_tokens,), NO_DRAFT, np.int32)
        j = len(self._hist) - 1
        for n in range(self.ngram_order, self.min_match - 1, -1):
            if j + 1 < n:
                continue
            key = (n, tuple(self._hist[j + 1 - n: j + 1]))
            pos = self._last.get(key)
            if pos == j:
                pos = self._prev.get(key)
            if pos is None:
                continue
            # Copy the continuation of the earlier occurrence.  When it
            # runs off the end of history (the match sits close to the
            # suffix — always true for constant runs and short cycles,
            # where the latest previous occurrence is the suffix minus
            # one period), extend periodically: a match at distance p
            # predicts hist[t] == hist[t - p], so fold the read index
            # back by the period instead of truncating the proposal.
            period = j - pos
            idx = pos + 1
            for i in range(self.spec_tokens):
                if idx > j:
                    idx -= period
                out[i] = self._hist[idx]
                idx += 1
            break
        return out

    @property
    def history_len(self) -> int:
        return len(self._hist)
