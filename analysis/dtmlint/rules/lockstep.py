"""collective-lockstep — collectives must be reachable on every host.

The deadlock shape this rule catches: a ``Consensus`` collective
(``broadcast_int`` / ``allgather_int`` / ``any_flag``) or raw
``process_allgather`` sitting under a branch whose predicate varies
*per process* (chief checks, process_index / rank / pid comparisons,
chaos host selection).  One host enters the collective, its peers never
do, and the fleet hangs until the watchdog fires — PR 4's chief-decides
consensus exists precisely because this class of bug shipped.

Fleet-uniform predicates (``nproc > 1``, ``process_count``,
``consensus.active``, ``world_size``) are fine: every host evaluates
them identically, so every host takes the same path.

Flagged shapes, for an ``if`` whose test mentions a per-process
identifier:

1. one branch performs a collective and the other (possibly absent)
   branch performs none;
2. neither branch performs a collective, but one branch exits the
   function early (``return``/``break``/``continue``) and a collective
   follows the ``if`` in the same scope — the exiting hosts never reach
   it.

Collectives *inside the test itself* are evaluated before the branch
and are therefore always uniform — not flagged.

"Performs a collective" is interprocedural (v2): a call that resolves
through the project call graph to a function whose transitive summary
contains a collective counts exactly like a direct
``consensus.broadcast_int`` — hiding the collective inside a helper no
longer hides it from the rule.  Unknown callees stay benign.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from analysis.dtmlint.astutil import (
    COLLECTIVE_CALLS,
    call_name,
    identifiers,
    terminates,
    walk_in_scope,
)
from analysis.dtmlint.callgraph import CallGraph, Ctx, iter_functions
from analysis.dtmlint.core import Finding, Project

RULE_ID = "collective-lockstep"

# Identifiers whose value differs between hosts of one fleet.  Matching
# is by bare name or attribute name, so ``self._is_chief``,
# ``jax.process_index()`` and ``os.getpid()`` all register.
PER_PROCESS = frozenset(
    {
        "is_chief",
        "_is_chief",
        "chief",
        "process_index",
        "process_id",
        "getpid",
        "pid",
        "rank",
        "_rank",
        "local_rank",
        "host_id",
        "host_index",
        "task_id",
        "chaos_host",
        "target_host",
        "is_coordinator",
    }
)


def _per_process_test(test: ast.AST) -> List[str]:
    return sorted(set(identifiers(test)) & PER_PROCESS)


def _collectives(cg: CallGraph, ctx: Ctx, node: ast.AST) -> List[Tuple]:
    """``(call, label)`` for every collective reachable from ``node``:
    direct calls, plus calls resolving to helpers whose transitive
    summary performs one."""
    out: List[Tuple] = []
    for n in walk_in_scope(node):
        if not isinstance(n, ast.Call):
            continue
        nm = call_name(n)
        if nm in COLLECTIVE_CALLS:
            out.append((n, f"`{nm}`"))
            continue
        target = cg.resolve(n, ctx)
        if target is None:
            continue
        chain = cg.collective_chain(target)
        if chain:
            hops = (target.name,) + chain[:-1]
            via = " -> ".join(f"`{h}`" for h in hops)
            out.append((n, f"`{chain[-1]}` (inside helper {via})"))
    return out


def _collectives_after(
    cg: CallGraph, ctx: Ctx, scope: ast.AST, stmt: ast.If
) -> List[Tuple]:
    """Collectives lexically after ``stmt`` in the same statement list."""
    out: List[Tuple] = []
    for node in walk_in_scope(scope):
        for attr in ("body", "orelse", "finalbody"):
            seq = getattr(node, attr, None)
            if isinstance(seq, list) and stmt in seq:
                idx = seq.index(stmt)
                for later in seq[idx + 1:]:
                    out.extend(_collectives(cg, ctx, later))
                return out
    # top-level statement list of the scope itself
    seq = getattr(scope, "body", [])
    if stmt in seq:
        idx = seq.index(stmt)
        for later in seq[idx + 1:]:
            out.extend(_collectives(cg, ctx, later))
    return out


def check(project: Project):
    cg = CallGraph.of(project)
    for sf in project.scoped_files:
        scopes = [(sf.tree, Ctx(sf.rel))]
        for fi, fctx in iter_functions(sf):
            scopes.append(
                (
                    fi.node,
                    Ctx(
                        rel=fctx.rel,
                        cls=fctx.cls,
                        func_stack=fctx.func_stack + (fi.node,),
                    ),
                )
            )
        for scope, ctx in scopes:
            for node in walk_in_scope(scope):
                if not isinstance(node, ast.If):
                    continue
                markers = _per_process_test(node.test)
                if not markers:
                    continue
                in_body = [
                    c
                    for stmt in node.body
                    for c in _collectives(cg, ctx, stmt)
                ]
                in_orelse = [
                    c
                    for stmt in node.orelse
                    for c in _collectives(cg, ctx, stmt)
                ]
                why = f"per-process condition ({', '.join(markers)})"
                if bool(in_body) != bool(in_orelse):
                    # The collective-free side may still reach a
                    # collective by falling through to one after the
                    # `if` — that's the matched shape, not a deadlock.
                    empty_side = node.orelse if in_body else node.body
                    falls_through = not (
                        empty_side and terminates(empty_side)
                    )
                    if falls_through and _collectives_after(
                        cg, ctx, scope, node
                    ):
                        continue
                    bad, label = (in_body or in_orelse)[0]
                    yield Finding(
                        sf.rel,
                        bad.lineno,
                        RULE_ID,
                        f"collective {label} under {why} at "
                        f"line {node.lineno} has no matching collective "
                        "on the other path; hosts that skip this branch "
                        "never enter it (one-host deadlock)",
                    )
                    continue
                if in_body or in_orelse:
                    continue
                exits_body = terminates(node.body)
                exits_orelse = bool(node.orelse) and terminates(node.orelse)
                if exits_body == exits_orelse:
                    continue
                later = _collectives_after(cg, ctx, scope, node)
                if later:
                    yield Finding(
                        sf.rel,
                        node.lineno,
                        RULE_ID,
                        f"early exit under {why} skips collective "
                        f"{later[0][1]} at line "
                        f"{later[0][0].lineno}; exiting hosts never reach "
                        "it (one-host deadlock)",
                    )
