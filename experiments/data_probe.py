#!/usr/bin/env python
"""Probe the machine for real datasets and write DATA_AVAILABILITY.md.

Every convergence/A-B artifact in this repo is honest about running on
synthetic data; this probe is the companion evidence that real data was
actually *looked for* (VERDICT r2 "Missing #5": the accuracy-parity
corridors in SURVEY.md §6 are untestable without MNIST/CIFAR/ImageNet/PTB
on disk, and the repo should document that fact rather than assert it).

Checks the exact paths the dataset loaders read (data/datasets.py):
  - $DTM_DATA_DIR (default /root/data)/mnist.npz
  - .../cifar10.npz
  - .../imagenet/train-* + validation-* TFRecord shards
  - .../ptb.{train,valid,test}.txt
and records sizes/counts for whatever exists.
"""
# Runnable from anywhere (same idiom as recompute_mfu.py).
import glob
import json
import os
import sys
from datetime import datetime, timezone

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_tensorflow_models_tpu.data.datasets import DATA_DIR  # noqa: E402


def probe():
    checks = {}

    def record(name, paths, found, detail=""):
        checks[name] = {
            "paths_checked": paths,
            "found": found,
            "detail": detail,
        }

    # MNIST
    p = os.path.join(DATA_DIR, "mnist.npz")
    record("mnist", [p], os.path.isfile(p),
           f"{os.path.getsize(p)} bytes" if os.path.isfile(p) else "")

    # CIFAR-10 (loader reads one npz — datasets.py::load_cifar10)
    p = os.path.join(DATA_DIR, "cifar10.npz")
    record("cifar10", [p], os.path.isfile(p),
           f"{os.path.getsize(p)} bytes" if os.path.isfile(p) else "")

    # ImageNet TFRecords.  The loader falls back to synthetic PER SPLIT
    # (harness/train.py), so either split alone counts as "found" — the
    # detail records the per-split truth.
    tr = sorted(glob.glob(os.path.join(DATA_DIR, "imagenet", "train-*")))
    va = sorted(glob.glob(os.path.join(DATA_DIR, "imagenet", "validation-*")))
    record(
        "imagenet",
        [os.path.join(DATA_DIR, "imagenet", "{train,validation}-*")],
        bool(tr) or bool(va),
        f"{len(tr)} train / {len(va)} validation shards",
    )

    # PTB (loader reads DATA_DIR/ptb.{split}.txt and goes real for any
    # split whose file exists alongside ptb.train.txt —
    # datasets.py::load_ptb_tokens — so the train file alone means real
    # data is in use; the detail records the per-split truth).
    ptb = [
        os.path.join(DATA_DIR, f"ptb.{s}.txt")
        for s in ("train", "valid", "test")
    ]
    present = [os.path.basename(p) for p in ptb if os.path.isfile(p)]
    record(
        "ptb", ptb, os.path.isfile(ptb[0]),
        f"present: {', '.join(present) or 'none'}",
    )

    return {
        "data_dir": DATA_DIR,
        "data_dir_exists": os.path.isdir(DATA_DIR),
        "network_egress": _probe_egress(),
        "timestamp": datetime.now(timezone.utc).isoformat(),
        "datasets": checks,
    }


def _probe_egress(timeout=5.0):
    """Measured, not assumed: can this machine complete a real outbound
    HTTP fetch?  A bare TCP connect is NOT evidence — this machine's
    transparent proxy accepts the handshake and then walls the request
    (DNS fails, raw-IP HTTP returns 403) — so the probe requires an
    end-to-end 2xx/3xx response, which is what fetching a dataset would
    need."""
    import urllib.request

    for url in ("http://example.com/", "https://example.com/"):
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r:
                if 200 <= r.status < 400:
                    return True
        except Exception:  # noqa: BLE001 — any failure means no egress
            continue
    return False


def main():
    result = probe()
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "data_probe.json"), "w") as f:
        json.dump(result, f, indent=1)

    any_found = any(d["found"] for d in result["datasets"].values())
    lines = [
        "# Data availability on this machine",
        "",
        f"Probed {result['timestamp']} by `experiments/data_probe.py`.",
        f"`DTM_DATA_DIR` resolves to `{result['data_dir']}` "
        f"(directory {'exists' if result['data_dir_exists'] else 'ABSENT'}).",
        f"Outbound network egress (measured by end-to-end HTTP fetch): "
        f"{'yes' if result['network_egress'] else 'no'}.",
        "",
        "| dataset | found | paths checked | detail |",
        "|---|---|---|---|",
    ]
    for name, d in result["datasets"].items():
        lines.append(
            f"| {name} | {'YES' if d['found'] else 'no'} | "
            f"`{'`, `'.join(d['paths_checked'])}` | {d['detail']} |"
        )
    lines += [
        "",
        (
            "Real data present — convergence/accuracy artifacts can (and "
            "should) use it."
            if any_found
            else
            "No real dataset is present on this machine"
            + (
                " and the measured egress probe also failed, so none can "
                "be fetched"
                if not result["network_egress"]
                else " (egress exists — data could in principle be "
                "fetched, but no fetcher runs unattended here)"
            )
            + ".  The SURVEY.md §6 accuracy corridors (ResNet-50 75.9% "
            "top-1, PTB valid perplexity ~86) remain untestable here.  "
            "Every convergence/A-B artifact in this directory therefore "
            "runs on the deterministic synthetic substitutes from "
            "`data/datasets.py` and says so in its header; loaders switch "
            "to real data automatically the moment it appears under "
            "`DTM_DATA_DIR`."
        ),
        "",
    ]
    with open(os.path.join(here, "DATA_AVAILABILITY.md"), "w") as f:
        f.write("\n".join(lines))
    print(json.dumps(result))


if __name__ == "__main__":
    main()
