"""Helper that only reads the counter it is handed."""


def snapshot(counter):
    return counter.total
