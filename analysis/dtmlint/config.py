"""Repo-specific lint configuration.

Two entry points:

- :func:`repo_config` — the real tree: walks the package, ``scripts/``
  and ``analysis/``, and wires each rule's scope to the modules that
  motivated it (see ISSUE 9 / README "Static analysis").
- :func:`strict_config` — explicit file lists (fixtures, ad-hoc CLI
  paths): every given file is treated as maximally in-scope for every
  rule, so known-bad snippets trip their rule without needing to mirror
  the repo layout.
"""

from __future__ import annotations

import os

from analysis.dtmlint.core import LintConfig

PACKAGE = "distributed_tensorflow_models_tpu"

# Modules that must stay importable on a supervisor host with no
# accelerator stack installed.  KNOBS.md documents the same list.
JAX_FREE_ROOTS = (
    f"{PACKAGE}/launch.py",
    f"{PACKAGE}/resilience/backoff.py",
    f"{PACKAGE}/resilience/heartbeat.py",
    f"{PACKAGE}/serving/server.py",
    f"{PACKAGE}/serving/replay.py",
    f"{PACKAGE}/serving/admission.py",
    f"{PACKAGE}/serving/deploy.py",
    f"{PACKAGE}/telemetry/slo.py",
    f"{PACKAGE}/telemetry/timeseries.py",
)

# Modules whose behaviour feeds checkpointed state, dataset cursors, or
# replay decisions — wall-clock / unseeded randomness here breaks the
# bit-identical-recovery contract.
DETERMINISM_SCOPE = (
    f"{PACKAGE}/data/datasets.py",
    f"{PACKAGE}/data/tfrecord.py",
    f"{PACKAGE}/data/augment.py",
    f"{PACKAGE}/data/pipeline.py",
    f"{PACKAGE}/core/train_loop.py",
    f"{PACKAGE}/resilience/chaos.py",
    f"{PACKAGE}/parallel/async_ps.py",
    f"{PACKAGE}/parallel/backup.py",
    f"{PACKAGE}/harness/generate.py",
    # Serving replay surface (ISSUE 16): the scheduler's admission /
    # wave ordering must replay bit-identically, and SLO windows feed
    # breach forensics — wall-clock reads belong in timeseries.py
    # (deliberately NOT scoped: its rows carry ts_wall by design).
    f"{PACKAGE}/serving/scheduler.py",
    # Open-loop replayer (ISSUE 17): arrival offsets and prompt mixes
    # are part of the drill's replay contract — every token and every
    # inter-arrival gap must come from an explicit seed, and pacing
    # must never read a wall clock.
    f"{PACKAGE}/serving/replay.py",
    # Overload tier (ISSUE 19): admission / shed / backpressure /
    # autoscale decisions are pure arithmetic over explicit stamps — a
    # clock read here would make shed ordering and scale decisions
    # unreplayable from the flight record.
    f"{PACKAGE}/serving/admission.py",
    f"{PACKAGE}/telemetry/slo.py",
    # Continuous deployment (ISSUE 20): canary routing is a seeded
    # rid-hash and every gate / promote / rollback decision is pure
    # arithmetic over timestamps the server passes in — a clock read
    # here would make the deploy timeline unreplayable and could route
    # the same rid to different versions on different replicas.
    f"{PACKAGE}/serving/deploy.py",
)

METRIC_REGISTRY = f"{PACKAGE}/telemetry/registry.py"

# Source of truth for mesh axis names (AxisNames) — the collective-order
# rule checks hard-coded ``axis_name=`` literals against it.
MESH_AXIS_MODULE = f"{PACKAGE}/core/mesh.py"

DEFAULT_BASELINE = "analysis/baseline.json"

_LINT_DIRS = (PACKAGE, "scripts", "analysis")


def _walk_py(root: str) -> list:
    rels = []
    for d in _LINT_DIRS:
        top = os.path.join(root, d)
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(
                x for x in dirnames if x != "__pycache__"
            )
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    rel = os.path.relpath(os.path.join(dirpath, fn), root)
                    rels.append(rel.replace(os.sep, "/"))
    return rels


def repo_config(root: str) -> LintConfig:
    """Lint configuration for the actual repository at ``root``."""
    files = _walk_py(root)
    jax_free = list(JAX_FREE_ROOTS) + [
        f for f in files
        if f.startswith("scripts/") and f.count("/") == 1
    ]
    return LintConfig(
        root=root,
        files=tuple(files),
        jax_free_roots=tuple(jax_free),
        determinism_scope=DETERMINISM_SCOPE,
        metric_registry=METRIC_REGISTRY,
        mesh_axis_module=MESH_AXIS_MODULE,
        module_namespaces=("",),
    )


def strict_config(paths, root: str) -> LintConfig:
    """Maximal-scope configuration for an explicit file list.

    ``paths`` are absolute or cwd-relative; they are re-expressed
    relative to ``root`` (the common ancestor when linting fixtures).
    Every file is in the determinism scope and — when it is not a
    registry itself — in the jax-free zone, so each fixture exercises
    its rule directly.
    """
    rels = []
    namespaces = [""]
    for p in paths:
        ap = os.path.abspath(p)
        rel = os.path.relpath(ap, root).replace(os.sep, "/")
        rels.append(rel)
        # Let fixture-local imports resolve: the file's directory and
        # its parent both act as import namespaces, so both
        # ``import helper`` and ``from fixturedir import helper`` find
        # a sibling file.
        parent = os.path.dirname(rel)
        for ns in (parent, os.path.dirname(parent)):
            if ns and ns not in namespaces:
                namespaces.append(ns)
    return LintConfig(
        root=root,
        files=tuple(rels),
        jax_free_roots=tuple(rels),
        determinism_scope=tuple(rels),
        metric_registry=None,
        module_namespaces=tuple(namespaces),
    )
