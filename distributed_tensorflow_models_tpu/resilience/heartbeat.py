"""Fleet heartbeats: fast dead/stalled-peer detection for multi-host runs.

A killed or wedged host does not announce itself — its peers discover it
by blocking in the next collective until some transport timeout fires
(minutes), and an outer supervisor discovers nothing at all.  The
heartbeat layer makes both detections prompt and cheap:

- every process runs a :class:`HeartbeatWriter` — a daemon thread
  atomically rewriting ``<dir>/p<i>.json``
  (``{"pid", "time", "step", "phase"}``) every ``interval_s``; the train
  loop feeds it the current step via :func:`beat` at chunk boundaries
  and its lifecycle phase (``init``/``restore``/``compile``/``train``/
  ``save``) via :func:`set_phase`, so the file distinguishes "process
  alive but step frozen" (hung collective) from "process gone" (file
  goes stale entirely) — and a stale-heartbeat teardown can say *what*
  the host was doing when it froze without opening any trace;
- the supervisor (``launch.launch_local``) and the chief's in-run
  ``FleetHook`` read the directory back via :func:`read_fleet` /
  :func:`fleet_summary` — peers alive, heartbeat ages, per-host step
  positions and the slowest-host step lag (``fleet/*`` gauges).

The transport is deliberately plain files on the shared filesystem
(atomic rename per write): no sockets, no collective, readable by a
process that has never imported jax — which is exactly what the
supervisor is.  The ``DTM_HEARTBEAT_DIR`` env var carries the directory
from launcher to children; ``launch.initialize_from_env`` calls
:func:`start_from_env` before any heavy import so a child's first
heartbeat lands within ~one interval of spawn.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

log = logging.getLogger("dtm")

ENV_HEARTBEAT_DIR = "DTM_HEARTBEAT_DIR"

DEFAULT_INTERVAL_S = 1.0


def _path(directory: str, process_index: int) -> str:
    return os.path.join(directory, f"p{process_index}.json")


class HeartbeatWriter:
    """One per process: a daemon thread writing the heartbeat file.

    ``beat(step)`` is the train loop's chunk-boundary touch — a couple
    of attribute writes, never I/O on the hot path; the thread persists
    the latest step at its own cadence.  Writes are atomic
    (tmp + rename) so a reader never parses a torn file.
    """

    def __init__(
        self,
        directory: str,
        process_index: int,
        interval_s: float = DEFAULT_INTERVAL_S,
    ):
        self.directory = directory
        self.process_index = process_index
        self._interval = max(0.05, float(interval_s))
        self._step = -1  # -1 = process up, training not yet looping
        self._phase = "init"  # restore | compile | train | save | ...
        # Guards _step/_phase: beat()/set_phase() run on the train loop
        # while the writer thread snapshots both — without the lock a
        # set_phase between the two reads can pair step N with the
        # previous phase (and str/int attribute writes, though atomic in
        # CPython, carry no cross-thread visibility contract).
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self, step: int) -> None:
        with self._lock:
            self._step = int(step)

    def set_phase(self, phase: str) -> str:
        """Record the lifecycle phase (an uncontended lock + attribute
        write — hot-path safe); returns the previous phase so a scoped
        setter (the save path) can restore it."""
        with self._lock:
            prev, self._phase = self._phase, str(phase)
        return prev

    @property
    def phase(self) -> str:
        with self._lock:
            return self._phase

    def _write(self) -> None:
        with self._lock:
            step, phase = self._step, self._phase
        payload = {
            "pid": os.getpid(),
            "time": time.time(),
            "step": step,
            "phase": phase,
        }
        path = _path(self.directory, self.process_index)
        tmp = f"{path}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)
        except OSError:  # heartbeat must never kill the worker
            log.exception("heartbeat write failed at %s", path)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self._write()

    def start(self) -> "HeartbeatWriter":
        if self._thread is not None:
            return self
        os.makedirs(self.directory, exist_ok=True)
        self._write()  # first beat lands before the thread's first tick
        self._thread = threading.Thread(
            target=self._run, name=f"heartbeat-p{self.process_index}",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self._interval + 1.0)
            self._thread = None


# Process-wide writer (started once by launch.initialize_from_env; the
# train loop reaches it through beat()/active_writer()).
_writer: Optional[HeartbeatWriter] = None
_writer_lock = threading.Lock()


def start_from_env(process_index: int = 0) -> Optional[HeartbeatWriter]:
    """Start the process heartbeat when ``DTM_HEARTBEAT_DIR`` is set
    (idempotent).  Returns the writer, or None when heartbeats are off.
    """
    global _writer
    directory = os.environ.get(ENV_HEARTBEAT_DIR)
    if not directory:
        return None
    with _writer_lock:
        if _writer is None:
            _writer = HeartbeatWriter(directory, process_index).start()
        return _writer


def active_writer() -> Optional[HeartbeatWriter]:
    return _writer


def beat(step: int) -> None:
    """Chunk-boundary touch; no-op when heartbeats are off."""
    w = _writer
    if w is not None:
        w.beat(step)


def set_phase(phase: str) -> str:
    """Lifecycle-phase touch; returns the previous phase ("" when
    heartbeats are off, making restore-previous a harmless no-op)."""
    w = _writer
    if w is None:
        return ""
    return w.set_phase(phase)


def read_fleet(
    directory: str, num_processes: int, now: Optional[float] = None
) -> list[Optional[dict]]:
    """Per-process heartbeat views (index == process index): ``None``
    when the file does not exist / does not parse, else the payload plus
    ``age_s``.  Unreadable == never-started or torn mid-write — both
    read as "no heartbeat", which is what the staleness math wants."""
    now = time.time() if now is None else now
    out: list[Optional[dict]] = []
    for i in range(num_processes):
        try:
            with open(_path(directory, i)) as f:
                payload = json.load(f)
            payload["age_s"] = max(0.0, now - float(payload.get("time", 0.0)))
            out.append(payload)
        except (OSError, ValueError):
            out.append(None)
    return out


def fleet_summary(
    directory: str,
    num_processes: int,
    *,
    stale_after_s: float,
    now: Optional[float] = None,
    views: Optional[list] = None,
) -> dict:
    """The ``fleet/*`` gauge values: ``peers_alive`` (fresh heartbeat
    within ``stale_after_s``), ``heartbeat_age_s`` (worst age among
    processes that have ever beaten; missing files excluded — staleness
    of a never-started peer is the supervisor's launch-grace call, not
    a gauge), and ``step_lag`` (max − min step among alive peers that
    have entered the train loop).  Pass precomputed ``views`` (one
    :func:`read_fleet` snapshot) when the caller also inspects the
    per-peer details — one consistent snapshot, one round of I/O."""
    if views is None:
        views = read_fleet(directory, num_processes, now=now)
    ages = [v["age_s"] for v in views if v is not None]
    alive_steps = [
        int(v.get("step", -1))
        for v in views
        if v is not None and v["age_s"] <= stale_after_s
    ]
    looping = [s for s in alive_steps if s >= 0]
    return {
        "peers_alive": len(alive_steps),
        "heartbeat_age_s": max(ages) if ages else 0.0,
        "step_lag": (max(looping) - min(looping)) if looping else 0,
    }
