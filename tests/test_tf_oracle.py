"""Reference-semantics oracle tests against the installed TensorFlow 2.21.

SURVEY.md §4.5: the reference mount is empty, but the exact TF 1.x machinery
the reference composes ships in this environment — so the strongest available
parity check is to run the real ``tf.compat.v1`` optimizers / protocols
locally and compare our JAX implementations trajectory-for-trajectory.

Covers:
- update-rule parity for SGD / Momentum (+Nesterov) / RMSProp / Adam
  (TF gradient_descent.py:27, momentum.py:25, rmsprop.py:50, adam.py:28)
- ``tf.train.exponential_decay`` schedule parity (F16)
- ``clip_by_global_norm`` parity (F17, TF clip_ops.py:300)
- the full ``SyncReplicasOptimizer`` accumulator/token protocol (F3) driven
  on an in-process graph with threaded workers, compared against our
  compiled sync-DP step on an 8-device mesh (SURVEY.md §3.1-§3.2 → one psum)
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

tf = pytest.importorskip("tensorflow")
v1 = tf.compat.v1

from distributed_tensorflow_models_tpu.ops import optim

SHAPE = (4, 3)


def run_tf_optimizer(make_opt, grads, x0, lr_uses_step=False):
    """Apply a fixed gradient sequence with a tf.compat.v1 optimizer; return
    the variable trajectory."""
    with tf.Graph().as_default():
        var = v1.get_variable(
            "v", initializer=tf.constant(x0), dtype=tf.float32
        )
        gph = v1.placeholder(tf.float32, x0.shape)
        gstep = v1.train.get_or_create_global_step()
        opt = make_opt(gstep)
        apply_op = opt.apply_gradients(
            [(gph, var)], global_step=gstep if lr_uses_step else None
        )
        traj = []
        with v1.Session() as sess:
            sess.run(v1.global_variables_initializer())
            for g in grads:
                sess.run(apply_op, {gph: g})
                traj.append(sess.run(var))
    return np.stack(traj)


def run_optax(tx, grads, x0):
    params = jnp.asarray(x0)
    state = tx.init(params)
    traj = []
    for g in grads:
        updates, state = tx.update(jnp.asarray(g), state, params)
        params = optax.apply_updates(params, updates)
        traj.append(np.asarray(params))
    return np.stack(traj)


@pytest.fixture(scope="module")
def grads():
    rng = np.random.RandomState(7)
    return [rng.randn(*SHAPE).astype(np.float32) for _ in range(8)]


@pytest.fixture(scope="module")
def x0():
    return np.random.RandomState(3).randn(*SHAPE).astype(np.float32)


def assert_traj_close(ours, theirs, atol=1e-5, rtol=1e-5):
    np.testing.assert_allclose(ours, theirs, atol=atol, rtol=rtol)


def test_sgd_matches_tf(grads, x0):
    theirs = run_tf_optimizer(
        lambda _: v1.train.GradientDescentOptimizer(0.1), grads, x0
    )
    assert_traj_close(run_optax(optim.sgd(0.1), grads, x0), theirs)


@pytest.mark.parametrize("nesterov", [False, True])
def test_momentum_matches_tf(grads, x0, nesterov):
    theirs = run_tf_optimizer(
        lambda _: v1.train.MomentumOptimizer(
            0.05, 0.9, use_nesterov=nesterov
        ),
        grads,
        x0,
    )
    ours = run_optax(
        optim.tf_momentum(0.05, 0.9, use_nesterov=nesterov), grads, x0
    )
    assert_traj_close(ours, theirs)


@pytest.mark.parametrize("centered", [False, True])
def test_rmsprop_matches_tf(grads, x0, centered):
    """Pins the epsilon-inside-sqrt and ms-initialised-to-ones TF kernel
    details (SURVEY.md §4.2) with the slim Inception config values."""
    theirs = run_tf_optimizer(
        lambda _: v1.train.RMSPropOptimizer(
            0.045, decay=0.9, momentum=0.9, epsilon=1.0, centered=centered
        ),
        grads,
        x0,
    )
    ours = run_optax(
        optim.tf_rmsprop(
            0.045, decay=0.9, momentum=0.9, epsilon=1.0, centered=centered
        ),
        grads,
        x0,
    )
    assert_traj_close(ours, theirs)


def test_adam_matches_tf(grads, x0):
    theirs = run_tf_optimizer(
        lambda _: v1.train.AdamOptimizer(0.01), grads, x0
    )
    ours = run_optax(optim.adam(0.01), grads, x0)
    # TF folds bias correction into the step size, leaving epsilon
    # uncorrected; optax corrects before adding epsilon.  With eps=1e-8 and
    # O(1) gradients the trajectories agree to ~1e-6.
    assert_traj_close(ours, theirs, atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("staircase", [True, False])
def test_exponential_decay_matches_tf(staircase):
    steps = np.arange(0, 25)
    with tf.Graph().as_default():
        sph = v1.placeholder(tf.int64, ())
        lr = v1.train.exponential_decay(
            0.5, sph, decay_steps=7, decay_rate=0.6, staircase=staircase
        )
        with v1.Session() as sess:
            theirs = np.array([sess.run(lr, {sph: s}) for s in steps])
    sched = optim.exponential_decay(0.5, 7, 0.6, staircase=staircase)
    ours = np.array([float(sched(s)) for s in steps])
    np.testing.assert_allclose(ours, theirs, rtol=1e-6)


def test_clip_by_global_norm_matches_tf(grads):
    tree = {"a": grads[0], "b": grads[1] * 10.0}
    clipped_tf, norm_tf = v1.clip_by_global_norm(
        [tf.constant(tree["a"]), tf.constant(tree["b"])], 1.7
    )
    clip = optim.clip_by_global_norm(1.7)
    state = clip.init(tree)
    ours, _ = clip.update(jax.tree.map(jnp.asarray, tree), state)
    np.testing.assert_allclose(
        np.asarray(ours["a"]), clipped_tf[0].numpy(), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(ours["b"]), clipped_tf[1].numpy(), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(optim.global_norm(jax.tree.map(jnp.asarray, tree))),
        float(norm_tf.numpy()),
        rtol=1e-5,
    )


# ---------------------------------------------------------------------------
# SyncReplicasOptimizer protocol oracle
# ---------------------------------------------------------------------------


def run_tf_sync_replicas(w0, worker_batches, lr, n_steps):
    """Drive the real accumulator/token protocol (TF
    sync_replicas_optimizer.py:215-338) in-process.

    Linear regression ``loss_i = 0.5*(x_i . w - y_i)^2``; two worker threads
    share one session and each pushes its gradient per step; the chief
    queue-runner thread does take_grad(2) -> mean -> SGD apply -> token
    release.  Returns the weight trajectory (one entry per global step).
    """
    n_workers = len(worker_batches[0])
    dim = w0.shape[0]
    with tf.Graph().as_default():
        w = v1.get_variable("w", initializer=tf.constant(w0))
        xph = v1.placeholder(tf.float32, (None, dim))
        yph = v1.placeholder(tf.float32, (None,))
        loss = 0.5 * tf.reduce_mean(
            tf.square(tf.linalg.matvec(xph, w) - yph)
        )
        gstep = v1.train.get_or_create_global_step()
        opt = v1.train.SyncReplicasOptimizer(
            v1.train.GradientDescentOptimizer(lr),
            replicas_to_aggregate=n_workers,
            total_num_replicas=n_workers,
        )
        train_op = opt.minimize(loss, global_step=gstep)
        # num_tokens=0 (legal when total_num_replicas == replicas_to_aggregate)
        # starts with an EMPTY token queue, making the protocol strictly
        # lock-step.  The default (= replicas_to_aggregate pre-filled tokens
        # stamped with step 0, TF sync_replicas_optimizer.py:399-438) banks
        # tokens so workers run one step ahead; the accumulator then drops the
        # second step's gradients as stale — a startup transient of the
        # PS protocol that compiled SPMD sync intentionally does not have
        # (SURVEY.md §2.4: staleness handling disappears).
        init_tokens = opt.get_init_tokens_op(num_tokens=0)
        chief_qr = opt.get_chief_queue_runner()
        local_init = opt.chief_init_op
        ready = opt.ready_for_local_init_op

        traj = []
        with v1.Session() as sess:
            sess.run(v1.global_variables_initializer())
            sess.run(local_init)
            sess.run(init_tokens)
            coord = tf.train.Coordinator()
            threads = chief_qr.create_threads(sess, coord=coord, start=True)

            for step_batches in worker_batches:
                errs = []

                def worker(batch):
                    try:
                        x, y = batch
                        sess.run(train_op, {xph: x, yph: y})
                    except Exception as e:  # pragma: no cover
                        errs.append(e)

                ts = [
                    threading.Thread(target=worker, args=(b,))
                    for b in step_batches
                ]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(timeout=30)
                assert not errs, errs
                traj.append(sess.run(w))
            coord.request_stop()
            # The chief thread is blocked inside take_grad; only closing
            # the session cancels that pending op.  The resulting
            # CancelledError in the runner thread is the normal
            # end-of-training path for this protocol, not a failure.
            sess.close()
            try:
                coord.join(
                    threads,
                    stop_grace_period_secs=5,
                    ignore_live_threads=True,
                )
            except (
                tf.errors.CancelledError,
                tf.errors.OutOfRangeError,
                tf.errors.AbortedError,
                RuntimeError,
            ):
                pass
    return np.stack(traj)


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_sync_replicas_protocol_matches_compiled_psum_step(mesh8):
    """The reference's entire sync stack (accumulators + token queue +
    chief thread, SURVEY.md §3.1-§3.2) must produce the same trajectory as
    our single compiled step whose gradient mean is a psum over the mesh."""
    from distributed_tensorflow_models_tpu.core import (
        sharding as shardlib,
        train_loop,
    )
    from distributed_tensorflow_models_tpu.core.train_state import TrainState
    import flax.linen as nn

    rng = np.random.RandomState(0)
    dim, per_worker, n_workers, n_steps, lr = 6, 8, 2, 4, 0.2
    w0 = rng.randn(dim).astype(np.float32)
    w_true = rng.randn(dim).astype(np.float32)

    worker_batches = []
    global_batches = []
    for _ in range(n_steps):
        xs = rng.randn(n_workers * per_worker, dim).astype(np.float32)
        ys = xs @ w_true
        worker_batches.append(
            [
                (
                    xs[i * per_worker : (i + 1) * per_worker],
                    ys[i * per_worker : (i + 1) * per_worker],
                )
                for i in range(n_workers)
            ]
        )
        global_batches.append({"x": xs, "y": ys})

    tf_traj = run_tf_sync_replicas(w0, worker_batches, lr, n_steps)

    class Linear(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            w = self.param(
                "w", lambda *_: jnp.asarray(w0), (dim,), jnp.float32
            )
            return x @ w

    model = Linear()

    def loss_fn(params, state, batch, rngs):
        pred = model.apply({"params": params}, batch["x"])
        loss = 0.5 * jnp.mean(jnp.square(pred - batch["y"]))
        return loss, {"metrics": {"loss": loss}}

    state = TrainState.create(
        model, optim.sgd(lr), jax.random.key(0), jnp.zeros((2, dim))
    )
    state = train_loop.place_state(state, mesh8)
    step = train_loop.make_train_step(loss_fn)

    jax_traj = []
    for batch in global_batches:
        state, _ = step(state, shardlib.shard_batch(mesh8, batch), jax.random.key(0))
        jax_traj.append(np.asarray(state.params["w"]))

    # The TF protocol averages the two per-worker mean-gradients; the
    # compiled step takes the global-batch mean — identical for equal-sized
    # worker batches (SURVEY.md §2.4 sync row).
    np.testing.assert_allclose(
        np.stack(jax_traj), tf_traj, atol=1e-5, rtol=1e-5
    )
