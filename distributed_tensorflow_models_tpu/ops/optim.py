"""Optimizers and LR schedules with the reference's exact update semantics.

The reference trains with TF 1.x optimizers (SURVEY.md §2.2 F6):
``GradientDescentOptimizer`` (PTB, MNIST), ``MomentumOptimizer`` (CIFAR,
ResNet-50), ``RMSPropOptimizer`` (Inception-v3; TF rmsprop.py:50), wrapped in
``SyncReplicasOptimizer`` for sync data parallelism.  Here each is an
``optax.GradientTransformation``; the SyncReplicas wrapper has no equivalent
because gradient aggregation is compiled into the train step (SURVEY.md §7.1).

The update rules below are pinned to TF's kernels where they differ from
optax defaults — most importantly RMSProp's epsilon *inside* the square root
(SURVEY.md §4.2: "epsilon-inside-sqrt differences must be pinned by test").
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

ScalarOrSchedule = float | optax.Schedule


class TfRMSPropState(NamedTuple):
    count: jax.Array  # step counter, drives LR schedules
    ms: optax.Updates  # mean of squared gradients
    mom: optax.Updates  # momentum accumulator
    mg: Optional[optax.Updates]  # mean gradient (centered variant only)


def tf_rmsprop(
    learning_rate: ScalarOrSchedule,
    decay: float = 0.9,
    momentum: float = 0.9,
    epsilon: float = 1.0,
    centered: bool = False,
) -> optax.GradientTransformation:
    """RMSProp with TF-1.x kernel semantics (TF rmsprop.py:50).

    Per-variable update, exactly as the TF C++ kernel (and unlike optax's
    default, epsilon sits *inside* the sqrt)::

        ms  <- decay * ms + (1 - decay) * g^2
        mom <- momentum * mom + lr * g / sqrt(ms - mg^2? + epsilon)
        var <- var - mom

    The defaults (decay=0.9, momentum=0.9, epsilon=1.0) are the slim
    Inception-v3 training configuration the reference uses (SURVEY.md §2.1
    R5).  ``ms`` is initialised to **ones** as in TF, not zeros — with
    epsilon=1.0 this materially changes the first steps.
    """

    def init(params):
        ms = jax.tree.map(jnp.ones_like, params)
        mom = jax.tree.map(jnp.zeros_like, params)
        mg = jax.tree.map(jnp.zeros_like, params) if centered else None
        return TfRMSPropState(
            count=jnp.zeros((), jnp.int32), ms=ms, mom=mom, mg=mg
        )

    def update(grads, state, params=None):
        del params
        lr = (
            learning_rate(state.count)
            if callable(learning_rate)
            else learning_rate
        )
        ms = jax.tree.map(
            lambda m, g: decay * m + (1.0 - decay) * jnp.square(g),
            state.ms,
            grads,
        )
        if centered:
            mg = jax.tree.map(
                lambda m, g: decay * m + (1.0 - decay) * g, state.mg, grads
            )
            denom = jax.tree.map(
                lambda m2, m1: m2 - jnp.square(m1) + epsilon, ms, mg
            )
        else:
            mg = None
            denom = jax.tree.map(lambda m2: m2 + epsilon, ms)
        mom = jax.tree.map(
            lambda mo, g, d: momentum * mo + lr * g * jax.lax.rsqrt(d),
            state.mom,
            grads,
            denom,
        )
        updates = jax.tree.map(lambda m: -m, mom)
        new_state = TfRMSPropState(
            count=state.count + 1, ms=ms, mom=mom, mg=mg
        )
        return updates, new_state

    return optax.GradientTransformation(init, update)


def tf_momentum(
    learning_rate: ScalarOrSchedule,
    momentum: float = 0.9,
    use_nesterov: bool = False,
) -> optax.GradientTransformation:
    """``tf.train.MomentumOptimizer`` semantics (TF momentum.py:25)::

        accum <- momentum * accum + g
        var   <- var - lr * accum            (heavy-ball)
        var   <- var - lr * (g + momentum * accum)   (nesterov)

    optax's ``trace`` matches this accumulator convention, so this is a thin
    assembly kept for explicitness.
    """
    return optax.chain(
        optax.trace(decay=momentum, nesterov=use_nesterov),
        _scale_by_neg_lr(learning_rate),
    )


def sgd(learning_rate: ScalarOrSchedule) -> optax.GradientTransformation:
    """``tf.train.GradientDescentOptimizer`` (TF gradient_descent.py:27)."""
    return _scale_by_neg_lr(learning_rate)


def adam(
    learning_rate: ScalarOrSchedule,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
) -> optax.GradientTransformation:
    """``tf.train.AdamOptimizer`` (TF adam.py:28).  TF applies the bias
    correction through the effective LR, mathematically identical to optax's
    ``scale_by_adam`` followed by LR scaling."""
    return optax.chain(
        optax.scale_by_adam(b1=b1, b2=b2, eps=eps),
        _scale_by_neg_lr(learning_rate),
    )


def _scale_by_neg_lr(learning_rate: ScalarOrSchedule):
    if callable(learning_rate):
        return optax.scale_by_learning_rate(learning_rate, flip_sign=True)
    return optax.scale(-learning_rate)


def exponential_decay(
    initial_lr: float,
    decay_steps: int,
    decay_rate: float,
    staircase: bool = True,
) -> optax.Schedule:
    """``tf.train.exponential_decay`` (TF legacy_learning_rate_decay.py:29):
    ``lr * decay_rate ** (step / decay_steps)``, floored to an integer power
    when ``staircase`` — the schedule used by the reference's Inception and
    CIFAR drivers (SURVEY.md §2.2 F16)."""
    return optax.exponential_decay(
        init_value=initial_lr,
        transition_steps=decay_steps,
        decay_rate=decay_rate,
        staircase=staircase,
    )


def zaremba_decay(
    initial_lr: float,
    steps_per_epoch: int,
    hold_epochs: int,
    decay_rate: float,
) -> optax.Schedule:
    """The PTB staged schedule (SURVEY.md §2.1 R8, Zaremba et al.):
    constant for the first ``hold_epochs`` epochs, then multiplied by
    ``decay_rate`` once per epoch —
    ``lr * decay_rate ** max(0, epoch + 1 - hold_epochs)`` with
    ``epoch = step // steps_per_epoch`` (the reference reassigns the LR
    variable at each epoch boundary with exactly this exponent)."""

    def schedule(count):
        epoch = count // steps_per_epoch
        exponent = jnp.maximum(0, epoch + 1 - hold_epochs)
        return initial_lr * decay_rate ** exponent.astype(jnp.float32)

    return schedule


def piecewise_constant(
    boundaries: list[int], values: list[float]
) -> optax.Schedule:
    """``tf.train.piecewise_constant`` — staged LR drops (PTB's per-epoch
    LR decay, SURVEY.md §2.1 R8, is expressed with this).

    TF semantics: ``values[i]`` while ``x <= boundaries[i]`` — the old value
    still applies *at* the boundary step and the drop lands at
    ``boundary + 1``.  optax scales at ``count >= boundary``, so boundaries
    are shifted by one here to pin the TF behavior.
    """
    if len(values) != len(boundaries) + 1:
        raise ValueError("need len(values) == len(boundaries) + 1")
    scales = {
        b + 1: values[i + 1] / values[i] for i, b in enumerate(boundaries)
    }
    return optax.piecewise_constant_schedule(values[0], scales)


def clip_by_global_norm(max_norm: float) -> optax.GradientTransformation:
    """``tf.clip_by_global_norm`` (TF ops/clip_ops.py:300) — the PTB driver
    clips gradients to global norm 5/10 before applying (SURVEY.md §2.2
    F17).  optax's transform implements the same rescale-if-exceeds rule."""
    return optax.clip_by_global_norm(max_norm)


def global_norm(tree) -> jax.Array:
    return optax.global_norm(tree)
