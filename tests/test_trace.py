"""Flight recorder / event tracer (telemetry/trace.py) and the fleet
timeline merger (scripts/fleet_report.py): ring semantics, Chrome-trace
well-formedness, flight-record schema (against the lint's validator),
the signal watcher's at-arrival dump, the chaos kill's dump-before-kill
ordering, heartbeat step/phase payloads, and cross-host incident /
relaunch / skew attribution — all cheap unit tests (no fits)."""

import json
import os
import signal
import threading
import time

import pytest

from distributed_tensorflow_models_tpu import resilience, telemetry
from distributed_tensorflow_models_tpu.resilience import chaos as chaoslib
from distributed_tensorflow_models_tpu.resilience import heartbeat

_SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


def _load_script(name):
    from importlib import util as importutil

    spec = importutil.spec_from_file_location(
        name, os.path.join(_SCRIPTS, f"{name}.py")
    )
    mod = importutil.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# --------------------------------------------------------------------------
# Ring semantics
# --------------------------------------------------------------------------


def test_ring_overwrites_oldest_and_counts_drops():
    t = telemetry.Tracer(capacity=4)
    for i in range(10):
        t.instant("e", {"i": i})
    events = t.events()
    assert len(events) == 4  # bounded
    assert [e["args"]["i"] for e in events] == [6, 7, 8, 9]  # newest kept
    assert t.emitted == 10
    assert t.dropped == 6


def test_disabled_tracer_records_nothing():
    t = telemetry.Tracer(capacity=8, enabled=False)
    t.instant("a")
    t.complete("b", 0.1)
    with t.span("c"):
        pass
    assert t.events() == []
    assert t.emitted == 0
    assert not telemetry.NULL_TRACER.enabled


def test_span_and_complete_durations():
    t = telemetry.Tracer(capacity=8)
    with t.span("work", {"k": 1}):
        time.sleep(0.01)
    t.complete("fixed", 2.5, args={"x": 1})
    by_name = {e["name"]: e for e in t.events()}
    assert by_name["work"]["ph"] == "X"
    assert by_name["work"]["dur_s"] >= 0.01
    assert by_name["work"]["args"] == {"k": 1}
    assert by_name["fixed"]["dur_s"] == 2.5
    # complete() backdates the start to now - dur.
    assert by_name["fixed"]["ts_mono"] < by_name["work"]["ts_mono"] + 10


def test_events_are_chronological_and_mono_per_thread():
    t = telemetry.Tracer(capacity=64)

    def emit(n):
        for i in range(n):
            t.instant("x", {"i": i})

    threads = [threading.Thread(target=emit, args=(10,)) for _ in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    events = t.events()
    monos = [e["ts_mono"] for e in events]
    assert monos == sorted(monos)
    per_tid: dict = {}
    for e in events:
        assert per_tid.get(e["tid"], -1) <= e["ts_mono"]
        per_tid[e["tid"]] = e["ts_mono"]


# --------------------------------------------------------------------------
# Registry attachment
# --------------------------------------------------------------------------


def test_registry_span_emits_trace_event_when_attached():
    reg = telemetry.MetricsRegistry()
    with reg.span("checkpoint/save"):  # default: NULL tracer, no events
        pass
    tracer = telemetry.Tracer(capacity=8)
    reg.trace = tracer
    with reg.span("checkpoint/save"):
        pass
    events = tracer.events()
    assert [e["name"] for e in events] == ["checkpoint/save"]
    assert events[0]["ph"] == "X"
    # The timer recorded both spans; the trace only the attached one.
    assert reg.snapshot()["checkpoint/save/count"] == 2


# --------------------------------------------------------------------------
# Chrome export + flight record (schema-checked by the lint's validator)
# --------------------------------------------------------------------------


def test_chrome_export_well_formed(tmp_path):
    t = telemetry.Tracer(capacity=16, process_index=3)
    t.instant("chaos/kill_at_step", {"step": 3})
    with t.span("train/data_wait"):
        pass
    path = str(tmp_path / "trace.json")
    t.dump_chrome(path)
    doc = json.loads(open(path).read())
    assert doc["displayTimeUnit"] == "ms"
    assert doc["otherData"]["process_index"] == 3
    assert doc["otherData"]["os_pid"] == os.getpid()
    events = doc["traceEvents"]
    meta = [e for e in events if e["ph"] == "M"]
    assert meta and meta[0]["args"]["name"] == "p3"
    real = [e for e in events if e["ph"] != "M"]
    assert all(e["pid"] == 3 for e in real)
    instants = [e for e in real if e["ph"] == "i"]
    completes = [e for e in real if e["ph"] == "X"]
    assert instants and instants[0]["s"] == "t"
    assert completes and completes[0]["dur"] >= 0
    assert all(isinstance(e["ts"], float) for e in real)


def test_flight_record_passes_schema_lint(tmp_path):
    lint = _load_script("check_metrics_schema")
    reg = telemetry.MetricsRegistry()
    tracer = telemetry.Tracer(capacity=16, process_index=1)
    reg.trace = tracer
    reg.counter("train/restarts").inc()
    with reg.span("checkpoint/fence"):
        pass
    tracer.instant("train/rollback", {"restored_step": 2})
    path = str(tmp_path / "flight_recorder_p1.json")
    tracer.dump_flight_record(path, "rollback", reg, extra={"step": 4})
    record = json.loads(open(path).read())
    assert lint.check_flight_record(record) == []
    assert record["reason"] == "rollback"
    assert record["step"] == 4
    assert record["process_index"] == 1
    assert record["registry"]["train/restarts"] == 1.0
    # The CLI path agrees with the library call.
    assert lint.main([path, "--flight-recorder"]) == 0


def test_flight_record_schema_catches_violations():
    lint = _load_script("check_metrics_schema")
    tracer = telemetry.Tracer(capacity=4)
    tracer.instant("a")
    good = tracer.flight_record("crash")
    assert lint.check_flight_record(good) == []

    missing = dict(good)
    del missing["reason"]
    assert any("reason" in e for e in lint.check_flight_record(missing))

    overflow = dict(good)
    overflow["events"] = [dict(good["events"][0])] * 10  # > capacity 4
    assert any("capacity" in e for e in lint.check_flight_record(overflow))

    backwards = json.loads(json.dumps(good))
    e0 = dict(backwards["events"][0])
    e1 = dict(e0)
    e1["ts_mono"] = e0["ts_mono"] - 1.0  # same tid, mono regression
    backwards["events"] = [e0, e1]
    assert any(
        "backwards" in e for e in lint.check_flight_record(backwards)
    )

    bad_dur = json.loads(json.dumps(good))
    bad_dur["events"] = [
        {**e0, "ph": "X", "dur_s": -1.0}
    ]
    assert any("dur_s" in e for e in lint.check_flight_record(bad_dur))


def test_metrics_schema_trace_prefix_nonnegative():
    lint = _load_script("check_metrics_schema")
    bad = [json.dumps({"step": 1, "time": 1.0, "trace/dropped": -1})]
    errors, _, _ = lint.check_lines(bad)
    assert any("trace" in e for e in errors)
    good = [json.dumps({"step": 1, "time": 1.0, "trace/dropped": 7})]
    errors, _, _ = lint.check_lines(good)
    assert not errors


# --------------------------------------------------------------------------
# FlightWatcher: dump at signal ARRIVAL (main thread not required to run)
# --------------------------------------------------------------------------


def test_flight_watcher_dumps_on_sigterm_arrival():
    """The watcher's contract: the dump fires off the wakeup fd when the
    signal lands — the graceful chunk-boundary poll is NOT involved (a
    host wedged in a dead peer's collective never reaches it)."""
    dumped = []
    done = threading.Event()

    def dump(reason):
        dumped.append(reason)
        done.set()

    # A Python-level handler must exist for the C handler (and so the
    # wakeup fd write) to be armed — same order fit uses: listener
    # first, watcher second.
    listener = resilience.PreemptionListener()
    assert listener.install()
    watcher = telemetry.FlightWatcher(dump)
    try:
        assert watcher.install()
        signal.raise_signal(signal.SIGTERM)
        assert done.wait(5.0), "watcher never dumped"
        assert dumped == [f"signal_{int(signal.SIGTERM)}"]
        assert listener.preempted  # the listener still saw the notice
    finally:
        watcher.stop()
        listener.uninstall()
    assert not any(
        t.name == "flight-watch" for t in threading.enumerate()
    )


def test_flight_watcher_install_off_main_thread_refuses():
    results = []

    def run():
        w = telemetry.FlightWatcher(lambda r: None)
        results.append(w.install())

    th = threading.Thread(target=run)
    th.start()
    th.join()
    assert results == [False]


# --------------------------------------------------------------------------
# Chaos kill: forensics BEFORE the SIGKILL
# --------------------------------------------------------------------------


def test_kill_hook_dumps_flight_record_before_sigkill(
    tmp_path, monkeypatch
):
    calls = []
    inj = chaoslib.ChaosInjector(
        chaoslib.ChaosConfig(kill_at_step=3), scope=str(tmp_path)
    )
    inj._process_index = 0  # the target host, no jax needed
    tracer = telemetry.Tracer(capacity=16)
    inj.tracer = tracer
    inj.flight_dump = lambda reason: calls.append(("dump", reason))
    monkeypatch.setattr(os, "kill", lambda *a: calls.append(("kill", a)))

    hook = inj.kill_hook()
    assert hook.wants_step(3)
    hook.after_step(None, {}, 3)
    assert [c[0] for c in calls] == ["dump", "kill"]  # dump strictly first
    assert calls[0][1] == "chaos_kill"
    fires = [e for e in tracer.events() if e["name"] == "chaos/kill_at_step"]
    assert fires and fires[0]["args"] == {"step": 3}
    # Durable marker written (the at-most-once contract is unchanged).
    assert inj._kill_fired()


# --------------------------------------------------------------------------
# Heartbeat payload: step + phase
# --------------------------------------------------------------------------


def test_heartbeat_payload_carries_step_and_phase(tmp_path):
    w = heartbeat.HeartbeatWriter(str(tmp_path), 0, interval_s=0.05)
    try:
        w.start()
        w.beat(7)
        prev = w.set_phase("save")
        assert prev == "init"
        w._write()
        view = heartbeat.read_fleet(str(tmp_path), 1)[0]
        assert view["step"] == 7
        assert view["phase"] == "save"
        assert w.set_phase(prev) == "save"  # scoped restore contract
    finally:
        w.stop()


# --------------------------------------------------------------------------
# fleet_report: merged timeline, incident + relaunch + skew attribution
# --------------------------------------------------------------------------


def _make_fleet_workdir(tmp_path) -> str:
    """Synthesize a 2-host kill incident: p1 killed at step 3 (flight
    record from os pid 111), both hosts relaunched (trace exports from
    different os pids), p1 lagging p0 by 2 steps mid-run."""
    workdir = str(tmp_path)
    os.makedirs(workdir, exist_ok=True)
    t0 = time.time()

    def chunk(tr, start, k, t, dur=0.05):
        tr.complete(
            "train/chunk", dur, ts_wall=t, ts_mono=t - t0 + 100.0,
            args={"start": start, "k": k},
        )

    # p1: the victim.  Chunks to step 3, the kill fire, the dump.
    t1 = telemetry.Tracer(capacity=64, process_index=1)
    for s in range(3):
        chunk(t1, s, 1, t0 + 0.2 * s)
    t1.instant("chaos/kill_at_step", {"step": 3})
    rec1 = t1.flight_record("chaos_kill", extra={"step": 3})
    rec1["pid"] = 111
    rec1["ts_wall"] = t0 + 0.7
    with open(os.path.join(workdir, "flight_recorder_p1.json"), "w") as f:
        json.dump(rec1, f)

    # p0: the survivor — SIGTERM'd by the supervisor, dumped at arrival.
    t0p = telemetry.Tracer(capacity=64, process_index=0)
    for s in range(5):
        chunk(t0p, s, 1, t0 + 0.15 * s)
    t0p.complete("train/data_wait", 0.4, ts_wall=t0 + 0.75)
    rec0 = t0p.flight_record("signal_15", extra={"step": 5})
    rec0["pid"] = 100
    rec0["ts_wall"] = t0 + 0.9
    with open(os.path.join(workdir, "flight_recorder_p0.json"), "w") as f:
        json.dump(rec0, f)

    # Relaunch traces (the completed second run) from NEW os pids.
    for proc, tracer, pid in ((0, t0p, 200), (1, t1, 222)):
        chrome = tracer.to_chrome()
        chrome["otherData"]["os_pid"] = pid
        with open(
            os.path.join(workdir, f"trace_p{proc}.json"), "w"
        ) as f:
            json.dump(chrome, f)
    return workdir


def test_fleet_report_names_killed_host_and_relaunch(tmp_path):
    fr = _load_script("fleet_report")
    workdir = _make_fleet_workdir(tmp_path)
    report = fr.build_report(workdir, min_span_ms=100.0)
    assert report["processes"] == [0, 1]

    by_proc = {e["proc"]: e for e in report["incidents"]}
    assert by_proc[1]["reason"] == "chaos_kill"
    assert by_proc[1]["step"] == 3
    assert by_proc[1]["relaunched"] is True
    assert by_proc[1]["relaunch_os_pid"] == 222
    assert by_proc[0]["reason"] == "signal_15"

    # Step skew: p0 reached 5 while p1 stopped at 3.
    skew = report["step_skew"]
    assert skew["lag"] == 2
    assert skew["laggard"] == 1 and skew["leader"] == 0

    # Stall attribution: p0's 0.4s data wait is the only long span.
    assert report["stalls"]["first"]["proc"] == 0
    assert report["stalls"]["first"]["name"] == "train/data_wait"

    text = fr.format_report(report)
    assert "KILLED" in text and "p1" in text
    assert "relaunched" in text

    # The merged Chrome trace stays loadable and rebases time.
    merged = fr.merge_chrome(fr.load_artifacts(workdir))
    json.dumps(merged)
    real = [e for e in merged["traceEvents"] if e["ph"] != "M"]
    assert {e["pid"] for e in real} == {0, 1}
    assert min(e["ts"] for e in real) == pytest.approx(0.0, abs=1.0)


def test_fleet_report_cli_smoke(tmp_path, capsys):
    fr = _load_script("fleet_report")
    workdir = _make_fleet_workdir(tmp_path / "wd")
    chrome_out = str(tmp_path / "fleet.json")
    json_out = str(tmp_path / "report.json")
    assert (
        fr.main([workdir, "--chrome", chrome_out, "--json", json_out]) == 0
    )
    out = capsys.readouterr().out
    assert "KILLED" in out
    assert json.load(open(json_out))["incidents"]
    assert json.load(open(chrome_out))["traceEvents"]


def test_fleet_report_empty_workdir(tmp_path, capsys):
    fr = _load_script("fleet_report")
    assert fr.main([str(tmp_path)]) == 0
    assert "no per-process artifacts" in capsys.readouterr().out
