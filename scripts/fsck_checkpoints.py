#!/usr/bin/env python
"""Crash-consistency check for a run's checkpoints (restore hardening,
offline form).

Validates every retained orbax step under ``<workdir>/checkpoints`` (or
a checkpoints dir given directly) with the same structural checks
``CheckpointManager.restore`` applies before auto-resume —
finalization marker, state-item metadata/manifest — plus the degraded
(non-fatal) per-process dataset-sidecar checks: unparseable JSON,
topology stamps that disagree with ``--process-count`` when given, and
— with ``--process-count`` — per-process sidecar *completeness* (a step
missing any peer's sidecar is not fleet-valid: the multi-host
chief-decided restore prefers the newest step where every process can
resume exactly; the report/JSON carry per-step ``sidecar_procs``,
``sidecar_nproc`` topology stamps, ``complete_for_nproc``, and
``fleet_valid``).  A step whose sidecar set is complete for a
*different* stamped process count is reported as a cross-topology
resume (resize) candidate rather than merely "missing peers" — the
elastic restore path picks candidates by that stamp.

Output: one line per step (``OK`` / ``TORN`` / ``DEGRADED``) and a
summary naming the step a hardened restore would actually use.  Exit 0
when the newest step is valid, 1 when restore would walk back (or
nothing is restorable), 2 on usage errors.

``--repair`` deletes torn step directories (and their sidecar dirs) so
the next run's ``latest_step`` is the newest *valid* step again — run it
after a crash leaves damage, or when the restore-hardening log told you
to.  ``--json`` emits the machine-readable report instead.

``--serving-candidate STEP`` answers a different question: is this
step adoptable by a live serving fleet?  It runs the exact pre-swap
gate ``serving/deploy.py`` applies — fleet-valid structure (fsck +
every process's dataset sidecar), finite weights, and (with
``--expected-signature``, a JSON ``[path, shape, dtype]`` list as
emitted by this mode or ``deploy.tree_signature``) avals-match against
the serving config.  Exit 0 = adoptable (usable as a deploy
pre-gate), 1 = rejected, with the reasons on stdout and a
``structural`` marker distinguishing "save may still be landing"
(retryable) from final NaN/aval rejections.

No jax/orbax import on the default path: safe on a login host against
live training dirs.  ``--serving-candidate`` restores the weight tree
and therefore imports orbax, function-level, only behind that flag.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as a script from anywhere
    sys.path.insert(0, _REPO)

from distributed_tensorflow_models_tpu.resilience import fsck  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "path",
        help="run workdir (containing checkpoints/) or a checkpoints dir",
    )
    p.add_argument(
        "--process-count", type=int, default=None,
        help="expected topology: flag sidecars stamped with a different "
        "process count (approximate-resume warning)",
    )
    p.add_argument(
        "--repair", action="store_true",
        help="delete torn step directories (and their dataset_states/) "
        "so latest_step becomes the newest valid step",
    )
    p.add_argument("--json", action="store_true", help="emit the raw report")
    p.add_argument(
        "--serving-candidate", type=int, default=None, metavar="STEP",
        help="run the serving deploy pre-gate on this step (fleet-valid "
        "+ finite + avals vs --expected-signature); exit 0 = adoptable",
    )
    p.add_argument(
        "--expected-signature", default=None, metavar="SIG_JSON",
        help="with --serving-candidate: JSON [path, shape, dtype] list "
        "the candidate's weight tree must match exactly (produce one "
        "by running --serving-candidate WITHOUT this flag, or from a "
        "live engine via serving.deploy.tree_signature)",
    )
    args = p.parse_args(argv)
    if args.expected_signature and args.serving_candidate is None:
        p.error("--expected-signature needs --serving-candidate")

    ckpt_dir = args.path
    nested = os.path.join(args.path, "checkpoints")
    if os.path.isdir(nested):
        ckpt_dir = nested
    if not os.path.isdir(ckpt_dir):
        print(f"error: no checkpoint directory at {ckpt_dir}", file=sys.stderr)
        return 2

    if args.serving_candidate is not None:
        # Deploy pre-gate mode: the same admission the live follower
        # applies, runnable standalone (CI, an operator's shell, or a
        # deploy pipeline's gate step before pointing a fleet at it).
        from distributed_tensorflow_models_tpu.serving import (  # noqa: E402
            deploy as deploylib,
        )

        expected = None
        if args.expected_signature:
            with open(args.expected_signature) as f:
                expected = tuple(
                    (path, tuple(shape), dtype)
                    for path, shape, dtype in json.load(f)
                )
        params, reasons, structural = deploylib.gate_candidate(
            ckpt_dir, args.serving_candidate,
            process_count=args.process_count,
            expected_signature=expected,
        )
        verdict = {
            "step": args.serving_candidate,
            "adoptable": not reasons,
            "reasons": reasons,
            "structural": structural,
        }
        if params is not None and expected is None:
            # No reference to compare against: emit the candidate's own
            # signature, reusable verbatim as --expected-signature input.
            verdict["signature"] = [
                [path, list(shape), dtype]
                for path, shape, dtype in deploylib.tree_signature(params)
            ]
        if args.json:
            print(json.dumps(verdict, indent=1))
        else:
            if verdict["adoptable"]:
                print(f"step {args.serving_candidate}: ADOPTABLE")
            else:
                kind = "structural (retryable)" if structural else "final"
                print(
                    f"step {args.serving_candidate}: REJECTED ({kind})"
                )
                for reason in reasons:
                    print(f"    {reason}")
        return 0 if verdict["adoptable"] else 1

    report = fsck.fsck_checkpoints(ckpt_dir, args.process_count)
    repaired = []
    if args.repair:
        for entry in report["steps"]:
            if entry["valid"]:
                continue
            step = entry["step"]
            shutil.rmtree(os.path.join(ckpt_dir, str(step)), ignore_errors=True)
            shutil.rmtree(
                os.path.join(ckpt_dir, "dataset_states", str(step)),
                ignore_errors=True,
            )
            repaired.append(step)
        if repaired:
            report = fsck.fsck_checkpoints(ckpt_dir, args.process_count)
        report["repaired_steps"] = repaired

    if args.json:
        print(json.dumps(report, indent=1))
    else:
        for entry in report["steps"]:
            if not entry["valid"]:
                status = "TORN"
            elif entry["sidecar_issues"]:
                status = "DEGRADED"
            else:
                status = "OK"
            procs = entry["sidecar_procs"]
            stamped = entry.get("complete_for_nproc")
            detail = ""
            if args.process_count is not None:
                detail = (
                    f"  sidecars {len(procs)}/{args.process_count}"
                    f"{'' if entry['fleet_valid'] else '  NOT FLEET-VALID'}"
                )
                if stamped is not None and stamped != args.process_count:
                    detail += f"  COMPLETE FOR {stamped}-PROC (resize candidate)"
            elif procs:
                detail = f"  sidecars {procs}"
                if stamped is not None:
                    detail += f"  stamped nproc={stamped}"
            print(f"step {entry['step']:>10d}  {status}{detail}")
            for issue in entry["issues"]:
                print(f"    {issue}")
            for issue in entry["sidecar_issues"]:
                print(f"    (sidecar) {issue}")
        if repaired:
            print(f"repaired: removed torn steps {repaired}")
        if report["newest_valid_step"] is None:
            print("no restorable checkpoint")
        elif report["newest_valid_step"] != report["latest_step"]:
            print(
                f"restore would WALK BACK: newest step "
                f"{report['latest_step']} is torn; newest valid is "
                f"{report['newest_valid_step']}"
            )
        else:
            print(f"restore target: step {report['newest_valid_step']}")
        if (
            args.process_count is not None
            and report["newest_fleet_valid_step"] != report["newest_valid_step"]
        ):
            print(
                "multi-host restore would PREFER step "
                f"{report['newest_fleet_valid_step']} (newest with every "
                f"process's dataset sidecar; newer steps force peers onto "
                "the primary's approximate position)"
            )

    ok = (
        report["newest_valid_step"] is not None
        and report["newest_valid_step"] == report["latest_step"]
    ) or (
        # Repair that removed every (torn) step leaves a clean slate —
        # the next run fresh-inits; that's the repaired state, exit 0.
        args.repair
        and not report["steps"]
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
