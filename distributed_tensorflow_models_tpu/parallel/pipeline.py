"""Pipeline parallelism over the ``pipe`` mesh axis (GPipe-style).

The reference has no pipeline parallelism (SURVEY.md §2.4: "out of parity
scope; design note only") — this module is the framework's design-headroom
implementation of that note, in the TPU-idiomatic form: the microbatch
schedule is a ``lax.scan`` whose body computes one stage-step on every pipe
rank simultaneously and rotates activations to the next rank with
``lax.ppermute`` (compiled to ICI collective-permute).  No host-side
scheduler, no per-stage processes — one compiled SPMD program, exactly like
the rest of the framework (SURVEY.md §7.1).

Model contract: a *uniform* stage function ``stage_fn(stage_params, x) -> y``
(e.g. a transformer block, an MLP block, an LSTM layer) with per-stage
parameters stacked on a leading axis of size ``n_stages``.  The stacked
params shard over ``pipe`` so each device holds one stage's weights; the
batch is split into microbatches that stream through the ring.

Differentiability is free: ``ppermute`` has a transpose rule and the
schedule is a ``scan``, so ``jax.grad`` through :func:`pipeline_apply`
yields the full pipelined backward pass (GPipe's fill-drain schedule in
reverse) with no hand-written gradient code.  Composes with the ``data``
axis (microbatches themselves batch-sharded) and with remat
(``jax.checkpoint`` on ``stage_fn``) for activation memory.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_tensorflow_models_tpu.core.mesh import AxisNames

PyTree = jax.Array | dict | tuple | list


def split_microbatches(batch: jax.Array, num_microbatches: int) -> jax.Array:
    """[B, ...] → [M, B/M, ...].  B must divide evenly (static shapes —
    ragged microbatches would force recompilation, SURVEY.md §7 XLA
    semantics)."""
    b = batch.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible by num_microbatches {num_microbatches}"
        )
    return batch.reshape((num_microbatches, b // num_microbatches) + batch.shape[1:])


def merge_microbatches(x: jax.Array) -> jax.Array:
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])


def stack_stage_params(stage_params: list[PyTree]) -> PyTree:
    """[per-stage pytrees] → one pytree with leading stage axis, ready to
    shard over ``pipe`` (P('pipe', ...) on every leaf)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)


def pipeline_spec(params_stacked: PyTree, axis: str = AxisNames.PIPE):
    """PartitionSpecs placing each stage's weights on its pipe rank."""
    return jax.tree.map(lambda _: P(axis), params_stacked)


def pipeline_apply(
    stage_fn: Callable[[PyTree, PyTree], PyTree],
    params_stacked: PyTree,
    microbatches: PyTree,
    *,
    mesh: Mesh,
    axis: str = AxisNames.PIPE,
    data_axis: str | None = AxisNames.DATA,
):
    """Run ``microbatches`` [M, mb, ...] through the stage pipeline.

    ``microbatches`` may be a single array or a pytree whose leaves all
    carry the leading [M, mb] dims — e.g. ``(activations, mb_ids)`` so a
    stage can derive per-microbatch randomness (dropout keys) from data
    that travels *with* the activation through the ring; ``stage_fn`` must
    return the same structure.

    Schedule: ``M + n_stages - 1`` ticks.  At tick ``t`` every rank applies
    its stage to its current activation, then activations rotate one rank
    forward; rank 0 ingests microbatch ``t`` (while valid) and the last
    rank's outputs are collected from tick ``n_stages - 1`` on.  The bubble
    fraction is the usual GPipe ``(n-1)/(M+n-1)`` — pick ``M >= 4n`` to
    amortise.

    Composition with data parallelism is real, not nominal: the microbatch
    *batch* dimension shards over ``data_axis`` (each data slice pipelines
    its own slice of every microbatch; ``mb`` must divide the data-axis
    size), while stage weights shard over ``axis``.  Pass
    ``data_axis=None`` to replicate over data instead.

    Returns [M, mb, ...] outputs (sharded over ``data_axis``, replicated
    over ``axis``).
    """
    n_stages = mesh.shape[axis]
    num_mb = jax.tree.leaves(microbatches)[0].shape[0]
    total_ticks = num_mb + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def per_device(params, mbs):
        # params: [1, ...] — this rank's slice of the stage axis.
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        rank = lax.axis_index(axis)
        # The carry is device-varying from tick 1 on (rank-dependent
        # values); mark the zero init varying up front so the scan carry
        # type is stable (same pattern as ring attention's carries).
        state = jax.tree.map(
            lambda m: lax.pcast(jnp.zeros_like(m[0]), axis, to="varying"),
            mbs,
        )

        def tick(state, t):
            # Rank 0 ingests microbatch t; drain ticks (t >= M) re-feed a
            # clamped duplicate of microbatch M-1.  That duplicate is never
            # masked — it is correct only because it cannot reach the last
            # rank within total_ticks, so its outputs fall outside the
            # ys[n_stages-1:] collection window below.  Extending the scan
            # or collecting from another rank would break this invariant.
            x = jax.tree.map(
                lambda m, s: jnp.where(
                    rank == 0, m[jnp.minimum(t, num_mb - 1)], s
                ),
                mbs,
                state,
            )
            y = stage_fn(params, x)
            return jax.tree.map(
                lambda leaf: lax.ppermute(leaf, axis, perm), y
            ), y

        _, ys = lax.scan(tick, state, jnp.arange(total_ticks))
        # The last rank emitted microbatch m's result at tick m+n_stages-1:
        # a static slice of the scan's stacked outputs.  Replicate over the
        # ring: zero every rank but the last, then psum.
        return jax.tree.map(
            lambda leaf: lax.psum(
                jnp.where(
                    rank == n_stages - 1,
                    leaf[n_stages - 1 :],
                    jnp.zeros_like(leaf[n_stages - 1 :]),
                ),
                axis,
            ),
            ys,
        )

    mb_spec = P(None, data_axis) if data_axis else P()
    mb_specs = jax.tree.map(lambda _: mb_spec, microbatches)
    fn = jax.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(pipeline_spec(params_stacked, axis), mb_specs),
        out_specs=mb_specs,
    )
    return fn(params_stacked, microbatches)


def sequential_apply(
    stage_fn: Callable[[PyTree, PyTree], PyTree],
    params_stacked: PyTree,
    microbatches: PyTree,
) -> PyTree:
    """Reference semantics for tests/single-device: the same stages applied
    back-to-back with no pipelining.  Accepts the same array-or-pytree
    microbatches contract as :func:`pipeline_apply` (the two must stay
    interchangeable — tests pin them against each other)."""
    n_stages = jax.tree_util.tree_leaves(params_stacked)[0].shape[0]

    def one_mb(x):
        for i in range(n_stages):
            p = jax.tree.map(lambda q: q[i], params_stacked)
            x = stage_fn(p, x)
        return x

    return jax.vmap(one_mb)(microbatches)
