"""Known-bad: Python-value-dependence inside jitted code.

No module-level jax import on purpose (fixtures are linted as jax-free
roots in strict mode); the rule keys on the ``jax.jit`` spelling, not
on imports, and nothing here is ever executed.
"""


def step(state, n, flag):
    out = jnp.zeros(n)
    k = int(flag)
    if flag:
        out = out + k
    head = state[:n]
    return out, head


def helper(m):
    return m.item()


def outer(x):
    return helper(x) + len(x)


step_j = jax.jit(step)
outer_j = jax.jit(outer)
