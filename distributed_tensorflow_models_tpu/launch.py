"""Multi-process launcher — the L6 layer, TPU-native form.

The reference's outermost layer is per-model shell scripts that spawn N
``ps`` + M ``worker`` Python processes across hosts, passing ``--job_name``
and ``--task_index`` flags that each driver turns into a ``ClusterSpec`` +
``tf.train.Server`` (SURVEY.md §1 L6, §2.1 R1; TF training/server_lib.py:
96,107-146,242).  There is no resource manager — placement is manual.

The SPMD equivalent is radically smaller: every process runs the *same*
program; the only per-process facts are ``(coordinator_address,
num_processes, process_id)``, wired into ``jax.distributed.initialize``
(control plane only — the data plane is compiled XLA collectives over
ICI/DCN, SURVEY.md §5.8).  This module provides:

- the ``DTM_*`` environment convention carrying those three facts
  (the analogue of R1's ``--job_name/--task_index`` flags),
- :func:`initialize_from_env` — process-side bootstrap,
- :func:`launch_local` — spawn an N-process cluster on localhost
  (the analogue of TF's in-process fake clusters via
  ``Server.create_local_server``, SURVEY.md §4: multi-node protocol tests
  on one machine with no real cluster), now a *supervisor*: children
  heartbeat (``resilience/heartbeat.py``) and a dead or stalled child
  tears the fleet down in seconds (SIGTERM → grace → SIGKILL) instead
  of leaving survivors hung in collectives,
- :func:`supervise_local` — the fleet restart loop (relaunch +
  checkpoint auto-resume, deterministic-jitter backoff),
- a CLI: ``python -m distributed_tensorflow_models_tpu.launch``.

On managed TPU slices none of this is needed — ``jax.distributed
.initialize()`` auto-detects the slice topology and each host runs the same
command; use the CLI only for manual clusters and localhost tests.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import subprocess
import sys
from typing import Mapping, Optional, Sequence

log = logging.getLogger("dtm")

ENV_COORDINATOR = "DTM_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "DTM_NUM_PROCESSES"
ENV_PROCESS_ID = "DTM_PROCESS_ID"
ENV_CPU_DEVICES = "DTM_CPU_DEVICES_PER_PROCESS"

DEFAULT_PORT = 9671

# How long a SIGTERM'd fleet gets to drain (emergency checkpoints) before
# the supervisor SIGKILLs the stragglers.  A host hung in a dead peer's
# collective never reaches its chunk-boundary preemption poll — the KILL
# is what actually ends it; a healthy host exits resumable well inside
# the default.
DEFAULT_TERM_GRACE_S = 15.0
_MONITOR_POLL_S = 0.2

# Exit code a preempted-but-checkpointed training process uses (BSD
# EX_TEMPFAIL): the run wrote an emergency checkpoint on SIGTERM and
# rerunning the same command resumes it.  ``launch_local`` reports such
# children as resumable instead of replaying their logs as a failure,
# and propagates the code so outer supervisors can requeue.
RESUMABLE_EXIT_CODE = 75


def aggregate_exit_codes(codes) -> int:
    """Cluster exit code: a real failure always wins over "preempted"
    (one resumable child must not relabel another child's crash as
    resumable), preempted wins over success, all-zero is success."""
    failures = [c for c in codes if c not in (0, RESUMABLE_EXIT_CODE)]
    if failures:
        return max(failures)
    if RESUMABLE_EXIT_CODE in codes:
        return RESUMABLE_EXIT_CODE
    return 0


def initialize_from_env() -> bool:
    """Bootstrap ``jax.distributed`` from ``DTM_*`` env vars.

    Returns True if a multi-process cluster was configured, False when the
    env carries no cluster facts (single-process mode — the common case, and
    the analogue of running a reference driver without ``--job_name``).

    Must run before first backend use.  When ``DTM_CPU_DEVICES_PER_PROCESS``
    is set the process is forced onto that many fake CPU devices first
    (test clusters, SURVEY.md §4.3) and gloo cross-process collectives are
    enabled so psum/all-gather actually cross process boundaries.
    """
    cpu_devices = os.environ.get(ENV_CPU_DEVICES)
    if cpu_devices:
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={cpu_devices}"
        if "xla_force_host_platform_device_count" in flags:
            # Replace an inherited count (e.g. the test conftest's 8).
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", want, flags
            )
        else:
            flags = f"{flags} {want}".strip()
        os.environ["XLA_FLAGS"] = flags
        import jax

        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_cpu_collectives_implementation", "gloo")

    coord = os.environ.get(ENV_COORDINATOR)
    nproc = os.environ.get(ENV_NUM_PROCESSES)
    pid = os.environ.get(ENV_PROCESS_ID)

    # Fleet heartbeat (DTM_HEARTBEAT_DIR, set by the supervising
    # launcher): started HERE — before the heavy jax/backend imports
    # below — so the supervisor sees a first beat within ~a second of
    # spawn and a child that dies during initialization is still
    # attributable.  No-op when the env var is absent.
    from distributed_tensorflow_models_tpu.resilience import heartbeat

    heartbeat.start_from_env(int(pid) if pid else 0)

    if not (coord and nproc and pid):
        return False

    from distributed_tensorflow_models_tpu.core.mesh import (
        initialize_multihost,
    )

    initialize_multihost(
        coordinator_address=coord,
        num_processes=int(nproc),
        process_id=int(pid),
    )
    return True


def _terminate_fleet(
    procs: Sequence[subprocess.Popen],
    codes: dict[int, int],
    grace_s: float,
) -> None:
    """SIGTERM every still-running child (→ their preemption-grace
    emergency checkpoints, where reachable), wait up to ``grace_s``,
    SIGKILL the stragglers (a host hung in a dead peer's collective
    never reaches its chunk-boundary poll).  Fills ``codes``."""
    import time

    for i, p in enumerate(procs):
        if i not in codes and p.poll() is None:
            try:
                p.terminate()
            except OSError:  # already reaped
                pass
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        if all(
            i in codes or p.poll() is not None for i, p in enumerate(procs)
        ):
            break
        time.sleep(_MONITOR_POLL_S)
    for i, p in enumerate(procs):
        if i in codes:
            continue
        if p.poll() is None:
            sys.stderr.write(
                f"--- fleet: process {i} did not exit within the "
                f"{grace_s:.0f}s grace period; killing it ---\n"
            )
            p.kill()
            p.wait()
        codes[i] = p.returncode


def launch_local(
    num_processes: int,
    argv: Sequence[str],
    *,
    port: int = DEFAULT_PORT,
    cpu_devices_per_process: int | None = None,
    extra_env: Mapping[str, str] | None = None,
    timeout: float | None = None,
    heartbeat_timeout: float | None = None,
    term_grace_s: float = DEFAULT_TERM_GRACE_S,
    startup_stats: Optional[dict] = None,
) -> list[int]:
    """Spawn ``num_processes`` copies of ``argv`` as a localhost cluster.

    Each child gets the ``DTM_*`` cluster facts in its environment; process
    0's stdout/stderr pass through, the rest stream into temp files and are
    replayed only on failure (mirroring the reference launch scripts'
    per-task logs, R1).  Files, not pipes: a sequentially-drained pipe
    back-pressures a chatty child into blocking mid-step, which stalls the
    whole cluster at its next collective.  ``timeout`` bounds the *total*
    wall time of the cluster, not each child.  Returns the exit codes.

    **Supervision.**  The launcher polls the fleet instead of waiting on
    children in order: the moment any child dies with a real failure
    (exit not 0/75 — e.g. a ``kill -9``), the survivors are SIGTERM'd
    promptly and SIGKILL'd after ``term_grace_s`` — seconds of teardown
    instead of every peer hanging to its collective timeout.  Each child
    also gets a heartbeat directory (``DTM_HEARTBEAT_DIR``;
    ``resilience/heartbeat.py`` — written by ``initialize_from_env``,
    stepped by ``fit``, and read back by the chief's ``fleet/*``
    gauges); with ``heartbeat_timeout`` set, a child whose heartbeat
    goes stale that long (wedged, not dead) triggers the same fleet
    teardown, attributed to its process index.  Only pass
    ``heartbeat_timeout`` for commands that actually heartbeat — i.e.
    anything calling ``initialize_from_env`` — and size it over the
    slowest expected gap (initial jax import + first XLA compile beat
    the interval automatically; the writer thread starts pre-import).

    **Startup MTTR.**  Pass ``startup_stats`` (a dict, filled in place
    per process index) to stamp the relaunch-to-first-step milestones
    off the heartbeat files: ``first_beat_s`` (spawn → first heartbeat,
    i.e. process up), ``loop_entry_s`` (spawn → step ≥ 0, i.e. restore +
    setup done, entering the train loop) and ``first_step_s`` (spawn →
    first observed step *advance* past the entry step).  Readings are at
    heartbeat-interval resolution — ``supervise_local`` prints them per
    relaunch, and the precise in-process numbers live in the workdir's
    ``telemetry.json`` ``startup`` section.  ``first_step_s`` may be
    absent when chunks outrun the heartbeat cadence (the first observed
    beat already carries an advanced step).
    """
    import shutil
    import tempfile
    import time

    from distributed_tensorflow_models_tpu.resilience import heartbeat

    procs: list[subprocess.Popen] = []
    logs: list = [None]
    hb_dir = tempfile.mkdtemp(prefix="dtm-heartbeat-")
    t0_wall = time.time()
    try:
        for i in range(num_processes):
            env = dict(os.environ)
            env[ENV_COORDINATOR] = f"127.0.0.1:{port}"
            env[ENV_NUM_PROCESSES] = str(num_processes)
            env[ENV_PROCESS_ID] = str(i)
            env[heartbeat.ENV_HEARTBEAT_DIR] = hb_dir
            if cpu_devices_per_process is not None:
                env[ENV_CPU_DEVICES] = str(cpu_devices_per_process)
            if extra_env:
                env.update(extra_env)
            log = None
            if i != 0:
                log = tempfile.TemporaryFile(
                    mode="w+", prefix=f"dtm-launch-{i}-"
                )
                logs.append(log)
            procs.append(
                subprocess.Popen(
                    list(argv),
                    env=env,
                    stdout=None if i == 0 else log,
                    stderr=None if i == 0 else subprocess.STDOUT,
                )
            )
        def _stamp_startup() -> None:
            """Relaunch-to-first-step milestones from the heartbeat
            files (see the docstring); called once per poll round.
            Times come from each beat's own write timestamp (payload
            ``time``), not this reader's clock — a milestone whose beat
            is only *observed* by a later poll (or the final read after
            the fleet exits) is still stamped at the moment it was
            written, bounded by the writer's ~1 s cadence."""
            for i, view in enumerate(
                heartbeat.read_fleet(hb_dir, num_processes)
            ):
                if view is None:
                    continue
                at = round(float(view.get("time", 0.0)) - t0_wall, 3)
                st = startup_stats.setdefault(i, {})
                st.setdefault("first_beat_s", at)
                step = int(view.get("step", -1))
                if step >= 0 and "loop_entry_s" not in st:
                    st["loop_entry_s"] = at
                    st["_entry_step"] = step
                if (
                    "loop_entry_s" in st
                    and "first_step_s" not in st
                    and step > st["_entry_step"]
                ):
                    st["first_step_s"] = at

        deadline = None if timeout is None else time.monotonic() + timeout
        codes: dict[int, int] = {}
        failure: Optional[tuple[int, str]] = None
        while len(codes) < num_processes:
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired(argv, timeout)
            if startup_stats is not None:
                _stamp_startup()
            for i, p in enumerate(procs):
                if i in codes:
                    continue
                rc = p.poll()
                if rc is None:
                    continue
                codes[i] = rc
                if rc not in (0, RESUMABLE_EXIT_CODE) and failure is None:
                    try:
                        why = f"died on {signal.Signals(-rc).name}"
                    except ValueError:
                        why = f"exited {rc}"
                    failure = (i, why)
            if failure is not None:
                break
            if heartbeat_timeout is not None and len(codes) < num_processes:
                views = heartbeat.read_fleet(hb_dir, num_processes)
                for i, p in enumerate(procs):
                    if i in codes:
                        continue
                    view = views[i]
                    age = (
                        view["age_s"]
                        if view is not None
                        else time.time() - t0_wall
                    )
                    if age > heartbeat_timeout:
                        # Step + phase from the heartbeat payload: the
                        # stall is attributed ("frozen at step 40 in
                        # phase save") without traces — the flight
                        # recorder / fleet_report.py pick up from here.
                        failure = (
                            i,
                            f"heartbeat stale for {age:.1f}s "
                            f"(> {heartbeat_timeout:.1f}s; last step "
                            f"{'?' if view is None else view.get('step')}, "
                            "phase "
                            f"{'?' if view is None else view.get('phase', '?')})",
                        )
                        break
            if failure is not None:
                break
            time.sleep(_MONITOR_POLL_S)
        if failure is not None:
            i, why = failure
            sys.stderr.write(
                f"--- fleet: process {i} {why}; terminating the rest of "
                "the fleet (survivors take the emergency-checkpoint "
                "grace path where reachable) ---\n"
            )
            # A stalled (still-running) culprit gets the same
            # SIGTERM-then-SIGKILL as its peers.
            _terminate_fleet(procs, codes, term_grace_s)
        if startup_stats is not None:
            # One last read: the final beats (written right up to child
            # exit) may carry the first step advance the poll missed.
            _stamp_startup()
            for st in startup_stats.values():
                st.pop("_entry_step", None)
        code_list = [codes[i] for i in range(num_processes)]
        for i, rc in enumerate(code_list):
            if rc == RESUMABLE_EXIT_CODE:
                # Preemption grace, not a failure: the child checkpointed
                # and asked to be rerun — don't dump its log as a crash.
                sys.stderr.write(
                    f"--- process {i} preempted (exit {rc}): "
                    "resumable — rerun the same command ---\n"
                )
            elif rc != 0 and i != 0:
                logs[i].seek(0)
                sys.stderr.write(
                    f"--- process {i} (exit {rc}) ---\n"
                    f"{logs[i].read()}\n"
                )
        return code_list
    except BaseException:
        for p in procs:
            if p.poll() is None:
                p.kill()
        raise
    finally:
        for log in logs:
            if log is not None:
                log.close()
        shutil.rmtree(hb_dir, ignore_errors=True)


def supervise_local(
    num_processes: int,
    argv: Sequence[str],
    *,
    max_restarts: int = 2,
    backoff_base_s: float = 1.0,
    backoff_max_s: float = 60.0,
    seed: int = 0,
    port: int = DEFAULT_PORT,
    resize_to: int | None = None,
    auto_resize: bool = False,
    **launch_kwargs,
) -> int:
    """``launch_local`` under the fleet restart loop: a fleet torn down
    for a real failure (one host killed/stalled) is relaunched — same
    command, so every child auto-resumes from the latest checkpoint —
    up to ``max_restarts`` times, spaced by the deterministic-jitter
    backoff ``recoverable_fit`` uses for in-process restarts
    (``resilience/backoff.py``).  Per-host failure attribution goes to
    stderr each round.  Returns the final aggregate exit code; an
    all-preempted fleet (aggregate 75) returns immediately — the fleet
    was *told* to die, and the rerun belongs to whoever told it.

    Each relaunch bumps the coordinator port by one: the dead chief's
    listener can linger in TIME_WAIT, and a bind failure would burn a
    whole restart on launcher misfortune.

    Every round stamps the fleet's startup MTTR (spawn → loop entry →
    first step, from the heartbeat files — ``launch_local``'s
    ``startup_stats``) to stderr, so a relaunch's recovery time is
    visible at the supervisor without opening the workdir; the precise
    per-process numbers are the ``startup`` section of each run's
    ``telemetry.json``.

    Elastic resize: ``resize_to=M`` relaunches every restart at M
    processes instead of N — the children's cross-topology restore
    (``harness/checkpoint.py``) reshards the arrays onto the new mesh
    and re-splits the dataset cursor, so a fleet that lost (or gained)
    capacity keeps training instead of crash-looping at a process count
    it can no longer field.  ``auto_resize=True`` shrinks the fleet by
    the number of distinct failed processes on each relaunch (floor 1)
    — the "capacity is not coming back" mode for preemptible hosts.
    Both compose with the persistent XLA compile cache / AOT startup
    path: the surviving hosts' caches hold the per-shard programs, so a
    resized relaunch pays a reshard, not a cold compile, when the new
    shapes were seen before.  The children must still satisfy the batch
    contract (global batch divisible by the new process and device
    counts) — pick M accordingly.
    """
    import time

    from distributed_tensorflow_models_tpu.resilience import backoff

    if resize_to is not None and resize_to < 1:
        raise ValueError(f"resize_to must be >= 1, got {resize_to}")
    attempt = 0
    cur_procs = num_processes
    while True:
        stats: dict = {}
        codes = launch_local(
            cur_procs, argv, port=port + attempt,
            startup_stats=stats, **launch_kwargs
        )
        if stats:
            worst = max(
                (
                    st.get("first_step_s") or st.get("loop_entry_s") or 0.0
                    for st in stats.values()
                ),
                default=0.0,
            )
            sys.stderr.write(
                f"--- fleet startup MTTR ("
                f"{'relaunch' if attempt else 'launch'} {attempt}): "
                f"slowest spawn→first-step {worst:.1f}s; per process "
                + " ".join(
                    f"p{i}={stats[i]}" for i in sorted(stats)
                )
                + " ---\n"
            )
        agg = aggregate_exit_codes(codes)
        if agg in (0, RESUMABLE_EXIT_CODE):
            return agg
        failed = {
            i: c
            for i, c in enumerate(codes)
            if c not in (0, RESUMABLE_EXIT_CODE)
        }
        attempt += 1
        if attempt > max_restarts:
            sys.stderr.write(
                f"--- fleet: giving up after {max_restarts} restart(s); "
                f"failed processes {failed} ---\n"
            )
            return agg
        delay = backoff.restart_backoff(
            attempt, base_s=backoff_base_s, max_s=backoff_max_s, seed=seed
        )
        next_procs = cur_procs
        if resize_to is not None:
            next_procs = resize_to
        elif auto_resize:
            # Treat each distinct failed process as capacity that is not
            # coming back; the resized fleet resumes cross-topology.
            next_procs = max(1, cur_procs - len(failed))
        if next_procs != cur_procs:
            sys.stderr.write(
                f"--- fleet: RESIZING {cur_procs} -> {next_procs} "
                "process(es) on relaunch; children resume across the "
                "topology change (arrays resharded, dataset cursor "
                "re-split to the fleet-minimum position) ---\n"
            )
            cur_procs = next_procs
        sys.stderr.write(
            f"--- fleet: process(es) {sorted(failed)} failed "
            f"(exit codes {failed}); relaunching the whole fleet in "
            f"{delay:.2f}s (restart {attempt}/{max_restarts}, "
            f"coordinator port {port + attempt}, {cur_procs} "
            "process(es)) ---\n"
        )
        time.sleep(delay)


def main(argv: Sequence[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distributed_tensorflow_models_tpu.launch",
        description=(
            "Launch a command as an N-process jax.distributed cluster. "
            "Localhost mode spawns all processes; multi-host mode "
            "(--process-id given) configures this process only — run the "
            "same command on every host with its own --process-id, like "
            "the reference's per-host launch scripts."
        ),
    )
    parser.add_argument("--num-processes", type=int, required=True)
    parser.add_argument(
        "--coordinator",
        default=f"127.0.0.1:{DEFAULT_PORT}",
        help="host:port of process 0's coordination service",
    )
    parser.add_argument(
        "--process-id",
        type=int,
        default=None,
        help="multi-host mode: this host's process index; omit for "
        "localhost mode (spawns all processes here)",
    )
    parser.add_argument(
        "--cpu-devices-per-process",
        type=int,
        default=None,
        help="force N fake CPU devices per process (test clusters)",
    )
    parser.add_argument(
        "--max-restarts",
        type=int,
        default=0,
        help="localhost mode: relaunch the whole fleet (auto-resuming "
        "from checkpoints) up to N times after a real failure — the "
        "fleet-level recoverable_fit (0 = launch once)",
    )
    parser.add_argument(
        "--resize-to",
        type=int,
        default=None,
        help="localhost mode, with --max-restarts: relaunch at this "
        "process count after a failure (elastic resize; children "
        "resume across the topology change from the latest checkpoint)",
    )
    parser.add_argument(
        "--auto-resize",
        action="store_true",
        help="localhost mode, with --max-restarts: shrink the fleet by "
        "the number of failed processes on each relaunch (floor 1) — "
        "assume lost capacity is not coming back",
    )
    parser.add_argument(
        "--heartbeat-timeout",
        type=float,
        default=None,
        help="localhost mode: tear the fleet down when any child's "
        "heartbeat goes stale this many seconds (stalled-host "
        "detection; only for commands that initialize_from_env)",
    )
    parser.add_argument(
        "--term-grace",
        type=float,
        default=DEFAULT_TERM_GRACE_S,
        help="seconds a SIGTERM'd fleet gets to write emergency "
        f"checkpoints before SIGKILL (default {DEFAULT_TERM_GRACE_S:g})",
    )
    parser.add_argument("command", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        parser.error("no command given (append: -- python your_driver.py)")

    host, sep, port_str = args.coordinator.rpartition(":")
    if not sep or not port_str.isdigit():
        parser.error(
            f"--coordinator must be host:port, got {args.coordinator!r}"
        )

    if args.process_id is None:
        if host not in ("127.0.0.1", "localhost"):
            parser.error(
                "localhost mode spawns every process here; a non-local "
                f"--coordinator host ({host!r}) requires --process-id "
                "(run once per host)"
            )
        if args.max_restarts > 0:
            return supervise_local(
                args.num_processes,
                command,
                max_restarts=args.max_restarts,
                port=int(port_str),
                resize_to=args.resize_to,
                auto_resize=args.auto_resize,
                cpu_devices_per_process=args.cpu_devices_per_process,
                heartbeat_timeout=args.heartbeat_timeout,
                term_grace_s=args.term_grace,
            )
        if args.resize_to is not None or args.auto_resize:
            parser.error(
                "--resize-to/--auto-resize only apply to the restart "
                "loop; add --max-restarts N"
            )
        codes = launch_local(
            args.num_processes,
            command,
            port=int(port_str),
            cpu_devices_per_process=args.cpu_devices_per_process,
            heartbeat_timeout=args.heartbeat_timeout,
            term_grace_s=args.term_grace,
        )
        return aggregate_exit_codes(codes)

    env = os.environ
    env[ENV_COORDINATOR] = args.coordinator
    env[ENV_NUM_PROCESSES] = str(args.num_processes)
    env[ENV_PROCESS_ID] = str(args.process_id)
    if args.cpu_devices_per_process is not None:
        env[ENV_CPU_DEVICES] = str(args.cpu_devices_per_process)
    os.execvp(command[0], command)


if __name__ == "__main__":
    sys.exit(main())
